"""Ring (context-parallel) attention tests on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fms_fsdp_tpu.models.configs import LlamaConfig
from fms_fsdp_tpu.models.llama import init_llama_params, llama_forward
from fms_fsdp_tpu.ops.attention import xla_attention
from fms_fsdp_tpu.ops.ring_attention import ring_attention
from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh


def _qkv(b, s, nq, nkv, h, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.normal(size=(b, s, nq, h)), jnp.float32),
        jnp.asarray(rng.normal(size=(b, s, nkv, h)), jnp.float32),
        jnp.asarray(rng.normal(size=(b, s, nkv, h)), jnp.float32),
    )


@pytest.mark.parametrize("cp", [2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full(cp, causal):
    mesh = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=cp)
    )
    q, k, v = _qkv(2, 64, 4, 2, 16)
    ref = xla_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_with_tensor_axis():
    mesh = build_mesh(
        MeshConfig(
            sharding_strategy="fsdp",
            context_parallel_size=2,
            tensor_parallel_size=2,
        )
    )
    q, k, v = _qkv(2, 32, 4, 2, 16, seed=1)
    ref = xla_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_grads(causal):
    mesh = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )
    q, k, v = _qkv(1, 32, 2, 2, 16, seed=2)

    g1 = jax.grad(
        lambda q, k, v: (ring_attention(q, k, v, mesh, causal=causal) ** 2).mean(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: (xla_attention(q, k, v, causal=causal) ** 2).mean(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_path_matches_full(causal):
    """Flash-eligible local chunks (s_local=256, h=128): the Pallas-partial
    path (interpret mode on CPU), not the einsum fallback."""
    mesh = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )
    q, k, v = _qkv(1, 512, 2, 1, 128, seed=3)
    ref = xla_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_path_grads(causal):
    """Gradients through the flash-partial path via the ring-level custom
    VJP (O(S/cp) residuals; kv re-streamed in the backward ring)."""
    mesh = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )
    q, k, v = _qkv(1, 512, 2, 1, 128, seed=4)  # nq=2/nkv=1: GQA group sweep

    g1 = jax.grad(
        lambda q, k, v: (ring_attention(q, k, v, mesh, causal=causal) ** 2).mean(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: (xla_attention(q, k, v, causal=causal) ** 2).mean(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_llama_forward_context_parallel():
    """Full model forward agrees between cp=1 and cp=2 meshes."""
    cfg = LlamaConfig(
        src_vocab_size=128,
        emb_dim=64,
        nheads=4,
        kvheads=2,
        nlayers=2,
        multiple_of=16,
        max_expected_seq_len=64,
    )
    params = init_llama_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)

    mesh1 = build_mesh(MeshConfig(sharding_strategy="fsdp"))
    mesh2 = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )
    a = jax.jit(
        lambda p, t: llama_forward(
            p, t, cfg, attn_impl="xla", compute_dtype=jnp.float32, mesh=mesh1
        )
    )(params, tokens)
    b = jax.jit(
        lambda p, t: llama_forward(
            p, t, cfg, attn_impl="xla", compute_dtype=jnp.float32, mesh=mesh2
        )
    )(params, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
