"""Chunked resumable state-transfer transport + drain-and-migrate
(serve/disagg/transport.py, RequestJournal chunk progress/replay,
router reprefill and preempt paths; docs/serving.md "Streaming
transport & drain").

Anchors, per the PR-20 contract:

- the FMSC chunk wire round-trips a frame byte-identical over a real
  socketpair, heals injected corruption and loss (CRC-dropped chunks
  retransmit on the backoff timer), backpressures via the
  in-flight-bytes cap, and surfaces retry exhaustion / channel loss as
  a typed TransportError — never a hang;
- a sender rebuilt mid-transfer over the journal's acked-seq set
  retransmits ONLY the unacked chunks (the resumability pin);
- the blob path stays byte-identical: the packed frame the chunked
  wire reassembles IS the frame the single-message relay carries, and
  the page codec round-trips its own output bit-exact;
- RequestJournal replay tolerates one torn TRAILING line (truncate and
  warn), raises on a torn mid-file line, keeps terminal rids terminal,
  requeues assigned rids, and restores chunk-level transfer progress;
- the router requeues a typed handoff_error reject for RE-PREFILL
  (clearing the unusable bytes) instead of failing terminally or
  crash-looping the resume, and a preempted replica's ``migrate``
  frames re-journal like handoffs (drain_migrations counted, no
  double-requeue when the preempted process then exits);
- mamba's slab codec survives drain-and-migrate with bit-identical
  greedy tokens, rejects version skew typed (naming both versions),
  and an import failure after allocation frees the pages and slab
  slice it touched (pool accounting unchanged).

The wire/journal/router tests are jax-free; the engine-level slab
tests mirror tests/test_serving_families.py's tiny fixtures. Run as a
dedicated CI step (deselected from the main sweep).
"""

import base64
import json
import socket

import pytest

from fms_fsdp_tpu.resilience.faults import configure_faults
from fms_fsdp_tpu.serve.disagg.transport import (
    KIND_ACK,
    KIND_DATA,
    ChunkReceiver,
    ChunkSender,
    DataChannel,
    TransportError,
    decode_frames,
    encode_chunk,
    next_transfer_id,
    split_payload,
)
from fms_fsdp_tpu.serve.fleet import (
    FleetConfig,
    FleetRouter,
    RequestJournal,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _clear_faults():
    configure_faults("")
    yield
    configure_faults("")


def _pair(clk, tx_label="wire", rx_label="peer"):
    a, b = socket.socketpair()
    return (
        DataChannel(a, label=tx_label, clock=clk),
        DataChannel(b, label=rx_label, clock=clk),
    )


def _drive_transfer(sender, tx_ch, rx_ch, clk, dt=0.2, max_iters=200):
    """Pump a transfer to completion over a socketpair; returns the
    receiver (created lazily from the first DATA frame, exactly the
    way the router/replica loops do)."""
    receiver = None
    for _ in range(max_iters):
        sender.pump()
        for m in rx_ch.pump():
            if m["kind"] == KIND_DATA:
                if receiver is None:
                    receiver = ChunkReceiver(
                        m["rid"], m["transfer_id"], m["total"],
                        label=rx_ch.label,
                    )
                receiver.on_chunk(m, rx_ch)
        for m in tx_ch.pump():
            if m["kind"] == KIND_ACK:
                sender.on_ack(m)
        if sender.done:
            break
        clk.t += dt
    assert sender.done, (
        f"transfer stuck: {len(sender.acked)}/{sender.total} acked"
    )
    return receiver


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_split_payload_covers_remainder_and_empty():
    data = bytes(range(256)) * 10
    chunks = split_payload(data, 1000)
    assert len(chunks) == 3 and len(chunks[-1]) == 560
    assert b"".join(chunks) == data
    assert split_payload(b"", 1000) == [b""]


def test_chunk_roundtrip_over_socketpair():
    clk = FakeClock()
    tx, rx = _pair(clk)
    payload = bytes(range(256)) * 1200  # ~300 KiB
    s = ChunkSender(
        tx, 7, next_transfer_id(), payload,
        chunk_bytes=16 * 1024, clock=clk, label="wire.tx",
    )
    r = _drive_transfer(s, tx, rx, clk, dt=0.0)  # clock still: no resends
    assert r.complete and r.assemble() == payload
    assert s.total == 19 and s.chunks_sent == 19
    assert s.chunks_resent == 0 and not s.resumed
    assert r.corrupt_dropped == 0 and r.duplicates == 0


def test_decode_frames_flags_corruption_and_resyncs():
    good = encode_chunk(KIND_DATA, 1, 2, 0, 3, b"hello world")
    # flip a payload byte after the CRC was computed
    mut = bytearray(good)
    mut[-8] ^= 0xFF
    msgs, consumed = decode_frames(bytes(mut))
    assert consumed == len(good)
    assert msgs[0]["corrupt"] is True
    # a trashed header (absurd payload_len) desyncs; the scanner must
    # recover the NEXT frame by scanning to its magic
    trashed = bytearray(good)
    trashed[21:25] = b"\xff\xff\xff\xff"  # payload_len field
    buf = bytes(trashed) + good
    msgs, consumed = decode_frames(buf)
    assert [m["corrupt"] for m in msgs] == [False]
    assert msgs[0]["payload"] == b"hello world"
    assert consumed == len(buf)


def test_receiver_reacks_duplicates_and_stores_once():
    clk = FakeClock()
    tx, rx = _pair(clk)
    frame = encode_chunk(KIND_DATA, 1, 5, 0, 1, b"abc")
    r = ChunkReceiver(1, 5, 1)
    msgs, _ = decode_frames(frame + frame)
    assert r.on_chunk(msgs[0], tx) is True
    assert r.on_chunk(msgs[1], tx) is False  # duplicate, re-acked
    assert r.duplicates == 1 and r.complete
    acks = rx.pump()
    assert [m["kind"] for m in acks] == [KIND_ACK, KIND_ACK]
    assert r.assemble() == b"abc"


# ---------------------------------------------------------------------------
# loss, corruption, backpressure, failure
# ---------------------------------------------------------------------------


def test_corrupt_chunks_dropped_unacked_and_healed_by_retransmit():
    clk = FakeClock()
    tx, rx = _pair(clk, tx_label="cor")
    configure_faults("handoff_chunk_corrupt:transport=cor.tx:times=2")
    payload = bytes(range(256)) * 10
    s = ChunkSender(
        tx, 3, next_transfer_id(), payload, chunk_bytes=512,
        clock=clk, label="cor.tx", backoff_s=0.1, max_backoff_s=0.5,
    )
    r = _drive_transfer(s, tx, rx, clk)
    assert r.assemble() == payload
    assert s.chunks_corrupted == 2 and r.corrupt_dropped == 2
    assert s.chunks_resent >= 2
    assert s.interrupted and s.resumed  # healed, not clean end-to-end


def test_dropped_chunks_healed_by_retransmit():
    clk = FakeClock()
    tx, rx = _pair(clk, tx_label="drp")
    configure_faults("handoff_chunk_drop:transport=drp.tx:times=3")
    payload = bytes(range(256)) * 8
    s = ChunkSender(
        tx, 4, next_transfer_id(), payload, chunk_bytes=512,
        clock=clk, label="drp.tx", backoff_s=0.1, max_backoff_s=0.5,
    )
    r = _drive_transfer(s, tx, rx, clk)
    assert r.assemble() == payload
    assert s.chunks_dropped == 3
    assert r.corrupt_dropped == 0  # drops never reach the wire


def test_inflight_bytes_cap_backpressures_first_attempts():
    clk = FakeClock()
    tx, _rx = _pair(clk)
    payload = b"x" * (10 * 1024)
    s = ChunkSender(
        tx, 1, next_transfer_id(), payload, chunk_bytes=1024,
        max_inflight_bytes=3 * 1024, clock=clk,
    )
    assert s.pump() == 3  # 4th chunk would exceed the unacked-bytes cap
    assert s.pump() == 0  # still nothing acked: no further sends


def test_retry_exhaustion_raises_transport_error():
    clk = FakeClock()
    tx, _rx = _pair(clk, tx_label="exh")
    configure_faults("handoff_chunk_drop:transport=exh.tx")
    s = ChunkSender(
        tx, 9, next_transfer_id(), b"y" * 64, retries=2,
        backoff_s=0.01, max_backoff_s=0.01, clock=clk, label="exh.tx",
    )
    with pytest.raises(TransportError, match="unacked after 2 retries"):
        for _ in range(10):
            s.pump()
            clk.t += 1.0


def test_closed_channel_raises_transport_error():
    clk = FakeClock()
    tx, _rx = _pair(clk)
    s = ChunkSender(tx, 2, next_transfer_id(), b"z" * 64, clock=clk)
    tx.close()
    with pytest.raises(TransportError, match="channel closed"):
        s.pump()


def test_transport_stall_parks_channel_without_blocking():
    clk = FakeClock()
    tx, rx = _pair(clk, tx_label="stallch")
    configure_faults("transport_stall:transport=stallch:seconds=4:times=1")
    frame = encode_chunk(KIND_DATA, 1, 1, 0, 1, b"q")
    tx.send(frame)  # returns immediately; bytes parked in the outbuf
    assert tx.stalls == 1 and tx.outbuf_bytes == len(frame)
    assert rx.pump() == []
    clk.t = 5.0  # stall expired (and times=1 keeps it from re-arming)
    assert tx.pump() == []  # flushes the parked frame
    got = rx.pump()
    assert len(got) == 1 and got[0]["payload"] == b"q"


# ---------------------------------------------------------------------------
# resumability: only unacked chunks ever touch the wire again
# ---------------------------------------------------------------------------


def test_resume_retransmits_only_unacked_chunks(tmp_path):
    """The acceptance pin: a mid-transfer router relaunch rebuilds the
    sender over the journal's chunk_ack events and the surviving
    receiver sees ONLY the chunks it never confirmed."""
    clk = FakeClock()
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path, clock=clk)
    payload = bytes(range(256)) * 40  # 10 chunks of 1 KiB
    rid, run_id = 0, "replica1-i0"
    tid = next_transfer_id()
    total = len(split_payload(payload, 1024))
    assert total == 10
    j.transfer_begin(rid, tid, total, len(payload), run_id=run_id)

    tx, rx = _pair(clk)
    s1 = ChunkSender(
        tx, rid, tid, payload, chunk_bytes=1024,
        max_inflight_bytes=4 * 1024, clock=clk,
    )
    s1.pump()  # the cap admits exactly 4 first-attempt chunks
    receiver = None
    for m in rx.pump():
        if receiver is None:
            receiver = ChunkReceiver(rid, tid, m["total"])
        receiver.on_chunk(m, rx)
    for m in tx.pump():
        if s1.on_ack(m):  # the router journals each NEW ack
            j.chunk_ack(rid, tid, m["seq"])
    assert len(s1.acked) == 4 and not s1.done
    j.close()  # the router process dies here, mid-transfer

    j2 = RequestJournal(path, clock=clk, resume=True)
    seed = j2.transfer_acks(tid)
    assert seed == {0, 1, 2, 3}
    # the relaunched router dials the SAME surviving incarnation: a
    # fresh channel, the same receiver state on the far side
    tx2, rx2 = _pair(clk)
    s2 = ChunkSender(
        tx2, rid, tid, payload, chunk_bytes=1024, acked=seed, clock=clk,
    )
    assert s2.resumed_from == 4 and s2.resumed
    resent_seqs = []
    for _ in range(50):
        s2.pump()
        for m in rx2.pump():
            resent_seqs.append(m["seq"])
            receiver.on_chunk(m, rx2)
        for m in tx2.pump():
            s2.on_ack(m)
        if s2.done:
            break
        clk.t += 0.2
    assert s2.done
    assert sorted(resent_seqs) == [4, 5, 6, 7, 8, 9]  # never 0-3
    assert s2.chunks_sent == 6
    assert receiver.complete and receiver.assemble() == payload


def test_journal_abort_transfers_voids_dead_incarnation():
    """Resume-with-seed is only sound toward the SAME incarnation: the
    death sweep aborts its transfers so a relaunched replica's empty
    receiver gets a full resend."""
    j = RequestJournal(clock=FakeClock())
    t1, t2 = next_transfer_id(), next_transfer_id()
    j.transfer_begin(0, t1, 5, 100, run_id="replica0-i0")
    j.transfer_begin(1, t2, 5, 100, run_id="replica1-i0")
    j.chunk_ack(0, t1, 0)
    assert j.abort_transfers("replica0-i0") == [t1]
    assert j.transfer_acks(t1) == set()  # voided
    assert j.transfer_acks(t2) == set()  # untouched (no acks yet)
    assert t2 in j.transfers and t1 not in j.transfers


# ---------------------------------------------------------------------------
# blob path stays byte-identical (the codec is transport-independent)
# ---------------------------------------------------------------------------


def test_blob_and_chunked_frames_byte_identical():
    import numpy as np

    from fms_fsdp_tpu.serve.disagg import pack_handoff, unpack_handoff

    header = {
        "codec": "pages", "codec_version": 1, "family": "llama",
        "quant": "none", "page_size": 8, "prompt": [3, 5, 7],
        "generated": [11], "seq_len": 4, "alloc_tokens": 4,
        "max_new_tokens": 6, "n_kv_heads": 2, "head_dim": 16,
        "n_layers": 2,
    }
    arrays = {
        "k": np.arange(2 * 1 * 8 * 2 * 16, dtype=np.float32).reshape(
            2, 1, 8, 2, 16
        ),
        "v": np.ones((2, 1, 8, 2, 16), np.float32),
    }
    wire = pack_handoff(header, arrays)
    # the codec round-trips its own output bit-exact (unpack -> repack)
    h2, a2 = unpack_handoff(wire)
    assert pack_handoff(h2, a2) == wire
    # and the chunked transport reassembles the SAME bytes the blob
    # path would have carried in one message
    clk = FakeClock()
    tx, rx = _pair(clk)
    s = ChunkSender(
        tx, 1, next_transfer_id(), wire, chunk_bytes=256, clock=clk,
    )
    r = _drive_transfer(s, tx, rx, clk, dt=0.0)
    assert r.assemble() == wire


# ---------------------------------------------------------------------------
# journal replay (router relaunch over an existing event log)
# ---------------------------------------------------------------------------


def _seed_journal(path, clk):
    j = RequestJournal(path, clock=clk)
    r0 = j.admit([1, 2, 3], 4)
    r1 = j.admit([5], 4)
    r2 = j.admit([6, 7], 4)
    for rid, rep in ((r0, 0), (r1, 1)):
        j.queued.remove(rid)
        j.assign(rid, rep, f"replica{rep}-i0")
    j.complete(r0, [9, 9])
    tid = next_transfer_id()
    j.transfer_begin(r1, tid, 8, 512, run_id="replica1-i0")
    j.chunk_ack(r1, tid, 0)
    j.chunk_ack(r1, tid, 2)
    j.close()
    return (r0, r1, r2), tid


def test_journal_replay_restores_records_and_transfers(tmp_path):
    clk = FakeClock()
    path = str(tmp_path / "j.jsonl")
    (r0, r1, r2), tid = _seed_journal(path, clk)
    j2 = RequestJournal(path, clock=clk, resume=True)
    assert j2.torn_tail_dropped == 0
    # terminal stays terminal: the dedup gate survives the relaunch
    assert j2.records[r0].state == "completed"
    assert j2.complete(r0, [9, 9]) is False  # late duplicate dropped
    # the assigned rid requeued (its incarnation's promise is void),
    # the never-assigned rid is still queued, admission order kept
    assert j2.records[r1].state == "queued"
    assert j2.records[r1].requeues == 1
    assert j2.records[r1].prompt == [5]  # replay can re-dispatch it
    assert list(j2.queued) == [r1, r2]
    # chunk progress restored, and fresh transfer ids never collide
    # with the journaled ones
    assert j2.transfer_acks(tid) == {0, 2}
    assert next_transfer_id() > tid
    # new admissions do not reuse replayed rids
    assert j2.admit([8], 2) == r2 + 1


def test_journal_replay_truncates_torn_tail_and_warns(tmp_path, capsys):
    clk = FakeClock()
    path = str(tmp_path / "j.jsonl")
    (r0, r1, r2), tid = _seed_journal(path, clk)
    with open(path, "a") as fh:
        fh.write('{"event":"chunk_ack","rid":1,"tr')  # crash mid-append
    j2 = RequestJournal(path, clock=clk, resume=True)
    assert j2.torn_tail_dropped == 1
    assert "torn record" in capsys.readouterr().err
    # the torn line is physically gone: every surviving line parses
    with open(path) as fh:
        for line in fh:
            json.loads(line)
    # and the replay result matches the untorn log's
    assert j2.records[r0].state == "completed"
    assert j2.transfer_acks(tid) == {0, 2}


def test_journal_replay_raises_on_torn_mid_file_line(tmp_path):
    clk = FakeClock()
    path = str(tmp_path / "j.jsonl")
    _seed_journal(path, clk)
    with open(path, "a") as fh:
        fh.write('{"event":"chunk_ack","rid":1,"tr\n')  # torn, NOT tail
        fh.write('{"event":"expire","rid":2,"t":0.0}\n')
    with pytest.raises(ValueError, match="torn record"):
        RequestJournal(path, clock=clk, resume=True)


# ---------------------------------------------------------------------------
# router: reprefill on typed handoff rejects, preempt drain-and-migrate
# ---------------------------------------------------------------------------


class HandoffFakeReplica:
    """Replica double that records every routed message. No
    data_channel and no terminate(): exercises the blob-transport and
    drain-message fallbacks the real subprocess replica upgrades."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.out = [{"type": "hb", "iterations": 0, "completed": 0,
                     "slots_busy": 0, "queue_depth": 0}]
        self.sent = []
        self.dead = None
        self.completed = 0

    def send(self, msg):
        if self.dead is not None:
            return False
        self.sent.append(msg)
        return True

    def hb(self):
        self.out.append({"type": "hb", "iterations": 1,
                         "completed": self.completed,
                         "slots_busy": 0, "queue_depth": 0})

    def recv(self):
        o, self.out = self.out, []
        return o

    def drain_final(self, timeout_s=1.0):
        return self.recv()

    def poll(self):
        return self.dead

    def kill(self):
        self.dead = -9

    def close(self):
        pass


def _router(clk, n=2, **cfg_kw):
    replicas = {}

    def spawn(ctx):
        r = HandoffFakeReplica(ctx)
        replicas[ctx["replica"]] = r
        return r

    cfg_kw.setdefault("n_replicas", n)
    cfg_kw.setdefault("max_inflight_per_replica", 2)
    cfg_kw.setdefault("stall_timeout_s", 50.0)
    cfg_kw.setdefault("restart_backoff_s", 0.1)
    router = FleetRouter(
        spawn, FleetConfig(**cfg_kw), clock=clk, log=lambda m: None
    )
    router.start()
    router.poll()  # ingest readiness heartbeats
    return router, replicas


def _last_of(replica, mtype):
    matches = [m for m in replica.sent if m["type"] == mtype]
    return matches[-1] if matches else None


def test_router_requeues_handoff_error_reject_for_reprefill():
    """Satellite: a typed decode-side import failure clears the
    journaled bytes and re-prefills instead of failing terminally or
    re-dispatching the same unusable frame."""
    clk = FakeClock()
    router, replicas = _router(clk, prefill_replicas=1)
    rid = router.submit([1, 2, 3], 4)
    clk.t += 0.5
    router.poll()  # dispatched to the prefill replica
    assert _last_of(replicas[0], "submit")["rid"] == rid
    blob = base64.b64encode(b"frame-bytes" * 50).decode()
    replicas[0].out.append({"type": "handoff", "rid": rid, "data": blob,
                            "bytes": 550, "ttft": 0.2})
    replicas[0].hb()
    replicas[1].hb()
    clk.t += 0.5
    router.poll()  # journaled + resumed onto the decode replica
    resume = _last_of(replicas[1], "resume")
    assert resume is not None and resume["data"] == blob  # blob knob
    replicas[1].out.append({
        "type": "reject", "rid": rid,
        "reason": "handoff_error: handoff codec version skew: frame "
                  "carries 'pages' version 2, this replica speaks "
                  "version 1",
    })
    replicas[1].hb()
    clk.t += 0.5
    router.poll()
    rec = router.journal.records[rid]
    # the unusable bytes are gone and the rid is back in rotation (it
    # may already have re-dispatched within the same poll)
    assert rec.handoff is None and rec.state in ("queued", "assigned")
    assert router.handoff_reprefills == 1
    assert router.stats()["handoff_reprefills"] == 1
    clk.t += 0.5
    router.poll()
    # the rid went back out as a FRESH prefill, not a resume
    resubmit = [m for m in replicas[0].sent if m["type"] == "submit"]
    assert [m["rid"] for m in resubmit] == [rid, rid]
    # a non-handoff reject on a fresh rid stays terminal
    rid2 = router.submit([1, 2], 4)
    clk.t += 0.5
    router.poll()
    replicas[0].out.append({"type": "reject", "rid": rid2,
                            "reason": "too_large"})
    replicas[0].hb()
    clk.t += 0.5
    router.poll()
    assert router.journal.records[rid2].state == "failed"


def test_router_preempt_migrates_streams_without_double_requeue():
    clk = FakeClock()
    router, replicas = _router(clk)
    rid = router.submit([2, 4, 6], 8)
    clk.t += 0.5
    router.poll()
    victim = router.journal.records[rid].replica
    sibling = 1 - victim
    router.preempt(victim)
    # the double has no terminate(): the router falls back to the
    # drain control message, and stops dispatching to the victim
    assert _last_of(replicas[victim], "drain") is not None
    rid2 = router.submit([9], 4)
    for rep in replicas.values():
        rep.hb()
    clk.t += 0.5
    router.poll()
    assert router.journal.records[rid2].replica == sibling
    # the victim packs the live stream and ships it back, then exits
    # clean with the preempted code
    blob = base64.b64encode(b"slab-frame" * 30).decode()
    replicas[victim].out.append({"type": "migrate", "rid": rid,
                                 "data": blob, "bytes": 300,
                                 "ttft": 0.1})
    replicas[victim].dead = 6  # EXIT_CODES["preempted"]
    clk.t += 0.5
    router.poll()
    rec = router.journal.records[rid]
    # the migrate frame was re-journaled with its bytes (the same poll
    # may already have resumed it onto the sibling)
    assert rec.handoff == blob
    assert rec.state in ("queued", "assigned")
    assert router.drain_migrations == 1
    assert router.stats()["drain_migrations"] == 1
    # the death sweep must NOT requeue the migrated rid again (it was
    # already re-journaled by the migrate frame, which counts as a
    # handoff, not a recompute requeue)
    assert rec.requeues == 0 and rec.handoffs == 1
    # the stream resumes on the sibling carrying the migrated bytes
    replicas[sibling].hb()
    clk.t += 0.5
    router.poll()
    resume = _last_of(replicas[sibling], "resume")
    assert resume is not None and resume["data"] == blob


def test_router_preempted_exit_relaunches_without_backoff():
    from fms_fsdp_tpu.resilience.supervisor import (
        default_replica_policies,
    )

    pol = default_replica_policies()
    assert pol["preempted"].restart and not pol["preempted"].backoff
    clk = FakeClock()
    router, replicas = _router(clk)
    first = replicas[0]
    first.dead = 6
    clk.t += 0.5
    router.poll()
    clk.t += 0.01  # no backoff: the relaunch is immediate
    router.poll()
    assert replicas[0] is not first  # fresh incarnation in the slot


def test_router_stats_carry_v15_transport_counters():
    clk = FakeClock()
    router, _ = _router(clk)
    s = router.stats()
    for key in ("handoff_retries", "chunks_resent", "transfers_resumed",
                "drain_migrations"):
        assert s[key] == 0


# ---------------------------------------------------------------------------
# engine-level: mamba slab migrate parity, version skew, pool accounting
# ---------------------------------------------------------------------------

jax = pytest.importorskip("jax")

import dataclasses  # noqa: E402

import numpy as np  # noqa: E402

from fms_fsdp_tpu.models.configs import MambaConfig  # noqa: E402
from fms_fsdp_tpu.models.llama import init_llama_params  # noqa: E402
from fms_fsdp_tpu.models.configs import LlamaConfig  # noqa: E402
from fms_fsdp_tpu.models.mamba import init_mamba_params  # noqa: E402
from fms_fsdp_tpu.serve.disagg import (  # noqa: E402
    HandoffError,
    pack_handoff,
    unpack_handoff,
)
from fms_fsdp_tpu.serve.engine import (  # noqa: E402
    ServeConfig,
    ServingEngine,
)

TINY_LLAMA = LlamaConfig(
    src_vocab_size=128, emb_dim=64, nheads=4, kvheads=2, nlayers=2,
    max_expected_seq_len=256,
)
TINY_MAMBA = MambaConfig(
    d_model=64, n_layer=2, vocab_size=128, d_state=16, headdim=16,
    chunk_size=8, attn_layer_idx=(), d_intermediate=128,
)
_attn = dataclasses.replace(
    TINY_MAMBA.attn_cfg, head_dim=16, num_heads=4, num_heads_kv=2,
    rotary_emb_dim=8,
)
TINY_HYBRID = dataclasses.replace(
    TINY_MAMBA, n_layer=3, attn_layer_idx=(1,), attn_cfg=_attn,
)


@pytest.fixture(scope="module")
def hybrid_params():
    return init_mamba_params(jax.random.PRNGKey(1), TINY_HYBRID)


@pytest.fixture(scope="module")
def llama_params():
    return init_llama_params(jax.random.PRNGKey(0), TINY_LLAMA)


def _engine(params, cfg, max_batch=2, max_seq=64, **kw):
    kw.setdefault("compute_dtype", "float32")
    kw.setdefault("attn_impl", "reference")
    kw.setdefault("page_size", 16)
    kw.setdefault("max_prefill_per_step", max_batch)
    scfg = ServeConfig(max_batch=max_batch, max_seq_len=max_seq, **kw)
    return ServingEngine(params, cfg, scfg)


def test_mamba_slab_drain_migrate_token_parity(hybrid_params):
    """A hybrid mamba stream packed MID-DECODE (conv window + fp32 SSD
    state + attention pages) and resumed on a sibling engine finishes
    with the uninterrupted engine's exact greedy tokens — the
    zero-recompute property planned eviction rides on."""
    prompt, max_new = [3, 5, 7, 11], 10
    ref = _engine(hybrid_params, TINY_HYBRID)
    rref = ref.submit(prompt, max_new)
    ref.run()
    baseline = list(rref.generated)
    assert len(baseline) == max_new

    src = _engine(hybrid_params, TINY_HYBRID)
    req = src.submit(prompt, max_new)
    for _ in range(4):
        src.step()
    assert req in src.live_requests()
    mid = len(req.generated)
    assert 0 < mid < max_new  # genuinely mid-stream
    data = src.pack_stream(req)
    assert data is not None
    header, arrays = unpack_handoff(data)
    assert header["codec"] == "mamba_slab"
    # the slab frame carries per-mamba-layer conv+ssd leaves (layers 0
    # and 2; layer 1 is attention) and the hybrid kv page leaves
    assert {"slab.0000.conv", "slab.0000.ssd", "slab.0002.conv",
            "slab.0002.ssd", "kv.k", "kv.v"} == set(arrays)
    assert arrays["slab.0000.ssd"].dtype == np.float32

    dst = _engine(hybrid_params, TINY_HYBRID)
    r2 = dst.submit_handoff(data)
    dst.run()
    assert list(r2.generated) == baseline


def test_slab_version_skew_is_typed_naming_both_versions(hybrid_params):
    src = _engine(hybrid_params, TINY_HYBRID)
    req = src.submit([2, 4, 6], 8)
    for _ in range(2):
        src.step()
    data = src.pack_stream(req)
    header, arrays = unpack_handoff(data)
    header["codec_version"] = 99
    bad = pack_handoff(header, arrays)
    dst = _engine(hybrid_params, TINY_HYBRID)
    with pytest.raises(
        HandoffError, match=r"version 99, this replica speaks version 1"
    ):
        dst.submit_handoff(bad)


def _tamper_import(engine, wire, leaf):
    """Admit ``wire``, then swap one leaf for an object-dtype array of
    the RIGHT shape: every pre-allocation check passes and the device
    write itself fails — the free-on-failure path."""
    req = engine.submit_handoff(wire)
    header, arrays, nbytes = req.handoff_in
    arrays = dict(arrays)
    arrays[leaf] = np.full(arrays[leaf].shape, "x", dtype=object)
    req.handoff_in = (header, arrays, nbytes)
    return req


def test_import_failure_frees_pages_typed_reject(llama_params):
    """Satellite: a HandoffError AFTER page allocation frees what the
    import touched — pool accounting identical to before the attempt —
    and surfaces as a typed take_failed entry, not a crash."""
    pe = _engine(llama_params, TINY_LLAMA, role="prefill")
    preq = pe.submit([3, 5, 7], 6)
    pe.run()
    wire = preq.handoff_out
    assert wire is not None

    de = _engine(llama_params, TINY_LLAMA, role="decode")
    free_before = de.cache.pages_free
    req = _tamper_import(de, wire, "k")
    de.step()
    failed = de.take_failed()
    assert [r.rid for r in failed] == [req.rid]
    assert req.state == "failed"
    assert req.fail_reason.startswith("handoff_error")
    assert "pages freed" in req.fail_reason
    assert de.cache.pages_free == free_before
    # the engine keeps serving: a clean import of the SAME frame works
    r2 = de.submit_handoff(wire)
    de.run()
    assert len(r2.generated) == 6


def test_slab_import_failure_frees_pages_and_zeroes_slab(hybrid_params):
    src = _engine(hybrid_params, TINY_HYBRID)
    req = src.submit([5, 10, 15], 8)
    for _ in range(3):
        src.step()
    wire = src.pack_stream(req)
    dst = _engine(hybrid_params, TINY_HYBRID)
    free_before = dst.cache.pages_free
    bad = _tamper_import(dst, wire, "slab.0000.conv")
    dst.step()
    failed = dst.take_failed()
    assert [r.rid for r in failed] == [bad.rid]
    assert "slab import failed" in bad.fail_reason
    assert dst.cache.pages_free == free_before  # hybrid pages freed too
    slab = dst.adapter.slab_slice(0)
    for layer in slab:
        for part in layer.values():
            assert not np.asarray(part).any()  # slab slice re-zeroed
