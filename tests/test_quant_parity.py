"""Quantized-training parity suite (ISSUE 6 acceptance).

Three contracts:

1. **Opt-in purity**: ``quantized_matmuls="none"`` + ``quantized_reduce
   ="none"`` is bit-identical to the seed step — the quantized-reduce
   machinery is never even invoked, and the traced program contains no
   int8/fp8 types.
2. **Loss parity**: 50-step CPU runs of tiny llama/mamba/mixtral
   configs in every GEMM quant mode (bf16 vs int8 vs int8_dgrad vs fp8
   vs fp8_dgrad) and every reduce wire format land within per-mode
   final-loss tolerances of the bf16 run.
3. **Backward contracts**: wgrad is unquantized with fp32 accumulation
   (bit-for-bit vs the unquantized matmul's dW on fp32 operands), and
   the reduce wire formats round-trip with bounded error + correct
   delayed-scaling state threading.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fms_fsdp_tpu.config import TrainConfig
from fms_fsdp_tpu.models.configs import (
    LlamaConfig,
    MambaAttnConfig,
    MambaConfig,
    MixtralConfig,
)
from fms_fsdp_tpu.ops.quant import (
    FP8_E4M3_MAX,
    FP8_E5M2_MAX,
    delayed_scale,
    expert_matmul,
    fp8_matmul,
    fp8_matmul_dgrad,
    leaf_amax,
    matmul,
    roll_amax_history,
    wire_roundtrip,
)
from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh
from fms_fsdp_tpu.parallel.mixed_precision import (
    REDUCE_QUANT_MODES,
    get_dtype_policy,
)
from fms_fsdp_tpu.parallel.sharding import (
    init_amax_state,
    quantized_grad_reduce,
)
from fms_fsdp_tpu.train.step import (
    init_train_state,
    make_optimizer,
    make_train_step,
)

# ---------------------------------------------------------------------------
# fp8 matmul numerics
# ---------------------------------------------------------------------------


def _xw(seed=0, t=64, d=256, f=128):
    kx, kw = jax.random.split(jax.random.PRNGKey(seed))
    x = jax.random.normal(kx, (2, t, d), jnp.float32)
    w = jax.random.normal(kw, (d, f), jnp.float32) * 0.02
    return x, w


def test_fp8_forward_close():
    x, w = _xw()
    ref = x @ w
    out = fp8_matmul(x, w)
    rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
    # e4m3 has a 3-bit mantissa: coarser than int8's 127-step grid
    assert rel < 0.05, rel


def test_fp8_backward_is_straight_through():
    """bf16-exact backward: the fp8 forward's VJP must be exactly the
    unquantized matmul's gradients at the same operands."""
    x, w = _xw()
    g = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 128), jnp.float32)

    def via(mm):
        _, vjp = jax.vjp(mm, x, w)
        return vjp(g)

    dx_q, dw_q = via(fp8_matmul)
    dx_r, dw_r = via(lambda x, w: x @ w)
    np.testing.assert_allclose(np.asarray(dx_q), np.asarray(dx_r), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dw_q), np.asarray(dw_r), rtol=1e-5)


def test_fp8_dgrad_close_to_exact():
    x, w = _xw()
    g = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 128), jnp.float32)
    _, vjp = jax.vjp(fp8_matmul_dgrad, x, w)
    dx_q, dw_q = vjp(g)
    _, vjp_r = jax.vjp(lambda x, w: x @ w, x, w)
    dx_r, dw_r = vjp_r(g)
    rel = float(jnp.linalg.norm(dx_q - dx_r) / jnp.linalg.norm(dx_r))
    # e5m2 gradient x e4m3 weight: 2-bit mantissa on the g side
    assert rel < 0.10, rel
    np.testing.assert_allclose(np.asarray(dw_q), np.asarray(dw_r), rtol=1e-5)


def test_fp8_zero_and_outlier_safe():
    """The pre-cast clamp is load-bearing: e4m3fn overflows to NaN and
    e5m2 to inf — a zero tensor and a huge-outlier tensor must both
    produce finite output."""
    assert not bool(
        jnp.any(jnp.isnan(fp8_matmul(jnp.zeros((1, 8, 64)),
                                     jnp.zeros((64, 32)))))
    )
    x = jnp.full((1, 8, 64), 1e30, jnp.float32)
    w = jnp.full((64, 32), 1e4, jnp.float32)
    assert bool(jnp.all(jnp.isfinite(fp8_matmul(x, w))))


@pytest.mark.parametrize("quant", ["fp8", "fp8_dgrad"])
def test_fp8_dispatch(quant):
    x, w = _xw()
    assert matmul(x, w, quant=quant).shape == (2, 64, 128)
    ex = jax.random.normal(jax.random.PRNGKey(0), (4, 2, 16, 64))
    ew = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 48)) * 0.02
    out = expert_matmul(ex, ew, quant=quant)
    assert out.shape == (4, 2, 16, 48)
    ref = jnp.einsum("ebcd,edf->ebcf", ex, ew)
    rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
    assert rel < 0.05, rel


def test_unknown_quant_mode_raises():
    x, w = _xw()
    with pytest.raises(ValueError, match="quantized_matmuls"):
        matmul(x, w, quant="int4")
    with pytest.raises(ValueError, match="quantized_matmuls"):
        expert_matmul(
            jnp.zeros((2, 1, 4, 8)), jnp.zeros((2, 8, 4)), quant="fp16"
        )


# ---------------------------------------------------------------------------
# wgrad contract: unquantized, fp32-accumulated, bit-exact on fp32 params
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["int8", "int8_dgrad", "fp8", "fp8_dgrad"])
def test_wgrad_bit_identical_to_unquantized_fp32(mode):
    """The optimizer-bound dW of every quantized mode is the straight-
    through (unquantized) weight gradient: on fp32 operands it must
    match the unquantized matmul's dW BIT-FOR-BIT (both are a single
    fp32-accumulated contraction of the same operands)."""
    x, w = _xw()
    g = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 128), jnp.float32)
    _, vjp = jax.vjp(lambda x, w: matmul(x, w, quant=mode), x, w)
    _, dw_q = vjp(g)
    _, vjp_r = jax.vjp(lambda x, w: x @ w, x, w)
    _, dw_r = vjp_r(g)
    assert dw_q.dtype == dw_r.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(dw_q), np.asarray(dw_r))


def test_wgrad_bf16_operands_accumulate_fp32():
    """With bf16 operands (the train step's compute dtype) dW must be
    the fp32-accumulated contraction rounded ONCE to bf16 — never a
    bf16-accumulated sum."""
    x, w = _xw(t=128, d=512, f=64)
    xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    g = jax.random.normal(
        jax.random.PRNGKey(2), (2, 128, 64), jnp.float32
    ).astype(jnp.bfloat16)
    _, vjp = jax.vjp(lambda x, w: matmul(x, w, quant="int8"), xb, wb)
    _, dw_q = vjp(g)
    assert dw_q.dtype == jnp.bfloat16
    lead = (0, 1)
    ref = jax.lax.dot_general(
        xb, g, ((lead, lead), ((), ())), preferred_element_type=jnp.float32
    ).astype(jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(dw_q), np.asarray(ref))


def test_expert_wgrad_bit_identical_to_unquantized_fp32():
    ex = jax.random.normal(jax.random.PRNGKey(0), (4, 2, 16, 64))
    ew = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 48)) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(2), (4, 2, 16, 48))
    _, vjp = jax.vjp(
        lambda x, w: expert_matmul(x, w, quant="int8_dgrad"), ex, ew
    )
    _, dw_q = vjp(g)
    _, vjp_r = jax.vjp(
        lambda x, w: jnp.einsum("ebcd,edf->ebcf", x, w), ex, ew
    )
    _, dw_r = vjp_r(g)
    np.testing.assert_array_equal(np.asarray(dw_q), np.asarray(dw_r))


# ---------------------------------------------------------------------------
# reduce wire formats
# ---------------------------------------------------------------------------


def test_wire_roundtrip_int8_bounded_error():
    g = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    rt = wire_roundtrip(g, "int8")
    assert rt.dtype == g.dtype
    # symmetric per-row absmax grid: error <= (row absmax)/127 per entry
    bound = jnp.max(jnp.abs(g), axis=-1, keepdims=True) / 127.0
    assert bool(jnp.all(jnp.abs(rt - g) <= bound + 1e-7))


def test_wire_roundtrip_fp8_bounded_error():
    g = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    rt = wire_roundtrip(g, "fp8")
    # e5m2: 2 mantissa bits -> relative step 2^-3 within a binade of the
    # scaled value; the practical bound is 12.5% of each row's absmax
    bound = jnp.max(jnp.abs(g), axis=-1, keepdims=True) * 0.125
    assert bool(jnp.all(jnp.abs(rt - g) <= bound + 1e-7))


def test_wire_roundtrip_vector_uses_per_tensor_scale():
    """1-D leaves (biases, norms) carry a per-tensor scale — a
    per-element scale would make the round-trip lossless and hide the
    wire format entirely."""
    g = jnp.array([1.0, -0.31, 0.007, 0.0], jnp.float32)
    rt = wire_roundtrip(g, "int8")
    assert rt.shape == g.shape
    assert not bool(jnp.array_equal(rt, g))  # lossy: one shared scale
    assert float(jnp.abs(rt - g).max()) <= 1.0 / 127.0 + 1e-7
    rt8 = wire_roundtrip(g, "fp8")
    assert rt8.shape == g.shape and bool(jnp.all(jnp.isfinite(rt8)))


def test_wire_roundtrip_zero_and_unknown():
    z = jnp.zeros((8, 8))
    for wire in ("int8", "fp8"):
        assert not bool(jnp.any(jnp.isnan(wire_roundtrip(z, wire))))
    with pytest.raises(ValueError, match="reduce wire"):
        wire_roundtrip(z, "int4")


def test_delayed_scale_bootstrap_and_roll():
    """An all-zero history (step 0 / fresh resume field) falls back to
    the current amax — the first step is dynamic, not clamped to 0 —
    and the history rolls newest-first."""
    hist = jnp.zeros((4,), jnp.float32)
    cur = jnp.float32(3.0)
    s = delayed_scale(hist, cur)
    np.testing.assert_allclose(float(s), 3.0 / FP8_E5M2_MAX, rtol=1e-6)
    hist = roll_amax_history(hist, cur)
    assert hist[0] == 3.0 and float(hist.sum()) == 3.0
    # with history, the window max governs (delayed, not current)
    s = delayed_scale(hist, jnp.float32(0.5))
    np.testing.assert_allclose(float(s), 3.0 / FP8_E5M2_MAX, rtol=1e-6)
    hist = roll_amax_history(hist, jnp.float32(7.0))
    assert hist[0] == 7.0 and hist[1] == 3.0


def test_delayed_wire_clamps_growing_amax():
    """Values past the delayed scale's representable range clamp
    finitely (a growing amax between history updates must not overflow
    e5m2 to inf)."""
    scale = jnp.float32(1.0 / FP8_E5M2_MAX)  # amax window said ~1.0
    g = jnp.array([[5.0, -0.5]], jnp.float32)  # 5x past the window
    rt = wire_roundtrip(g, "fp8_delayed", scale=scale)
    assert bool(jnp.all(jnp.isfinite(rt)))
    assert float(rt[0, 0]) == pytest.approx(1.0, rel=1e-6)  # clamped


def test_quantized_grad_reduce_dynamic_modes():
    grads = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (32, 64)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (64,)),
    }
    for mode in ("int8", "fp8"):
        out, state = quantized_grad_reduce(grads, mode, None)
        assert state is None
        for k in grads:
            assert out[k].shape == grads[k].shape
            np.testing.assert_array_equal(
                np.asarray(out[k]), np.asarray(wire_roundtrip(grads[k], mode))
            )
    with pytest.raises(ValueError, match="quantized_reduce"):
        quantized_grad_reduce(grads, "int4", None)


def test_quantized_grad_reduce_delayed_threads_amax():
    grads = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (32, 64)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (64,)),
    }
    state = init_amax_state(grads, history_len=4)
    keys = set(state["amax_history"])
    assert keys == {"g.w", "g.b"}
    out, new_state = quantized_grad_reduce(grads, "fp8_delayed", state)
    assert set(new_state["amax_history"]) == keys
    for k, g in grads.items():
        hist = new_state["amax_history"]["g." + k]
        np.testing.assert_allclose(
            float(hist[0]), float(leaf_amax(g)), rtol=1e-6
        )
        # step 0 bootstraps from its own amax: the round-trip is the
        # dynamic per-leaf wire
        ref = wire_roundtrip(
            g, "fp8_delayed", scale=delayed_scale(
                jnp.zeros((4,)), leaf_amax(g)
            )
        )
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(ref))


def test_policy_reduce_quant_validation():
    class Cfg:
        mixed_precision = True
        pure_bf16 = False
        quantized_reduce = "warp"

    with pytest.raises(ValueError, match="quantized_reduce"):
        get_dtype_policy(Cfg())
    for mode in REDUCE_QUANT_MODES:
        Cfg.quantized_reduce = mode
        assert get_dtype_policy(Cfg()).reduce_quant == mode
    # the preset itself is untouched when the knob is off
    Cfg.quantized_reduce = "none"
    assert get_dtype_policy(Cfg()).reduce_dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# train-step integration: tiny three-family runs
# ---------------------------------------------------------------------------

_LLAMA = LlamaConfig(
    src_vocab_size=128,
    emb_dim=64,
    nheads=4,
    kvheads=2,
    nlayers=2,
    multiple_of=16,
    max_expected_seq_len=64,
)
_MAMBA = MambaConfig(
    d_model=64,
    d_intermediate=128,
    n_layer=2,
    vocab_size=128,
    attn_layer_idx=(1,),
    attn_cfg=MambaAttnConfig(
        head_dim=16, num_heads=4, num_heads_kv=2, rotary_emb_dim=8
    ),
    d_state=16,
    headdim=16,
    chunk_size=16,
    pad_vocab_size_multiple=16,
)
_MIXTRAL = MixtralConfig(
    src_vocab_size=128,
    emb_dim=64,
    nheads=4,
    kvheads=2,
    nlayers=2,
    hidden_dim=96,
    num_experts=4,
    top_k=2,
    max_expected_seq_len=64,
)
_FAMILIES = {"llama": _LLAMA, "mamba": _MAMBA, "mixtral": _MIXTRAL}


_LOSS_CACHE = {}


def _losses(family, quant="none", reduce="none", steps=50):
    """Loss trajectory of a deterministic tiny run, cached across tests
    (the bf16 baselines are shared by several parity tests)."""
    key = (family, quant, reduce, steps)
    if key not in _LOSS_CACHE:
        _, losses = _run_tiny(family, quant=quant, reduce=reduce, steps=steps)
        _LOSS_CACHE[key] = losses
    return _LOSS_CACHE[key]


def _run_tiny(family, quant="none", reduce="none", steps=50, faults=None):
    """Deterministic tiny training run; returns (final state, losses)."""
    model_cfg = _FAMILIES[family]
    cfg = TrainConfig(
        sharding_strategy="fsdp",
        expert_parallel_size=2 if family == "mixtral" else 1,
        batch_size=1,
        seq_length=32,
        num_steps=max(steps, 10),
        learning_rate=3e-3,
        quantized_matmuls=quant,
        quantized_reduce=reduce,
        attention_kernel="xla",
        kernel_tuning="off",
        faults=faults or "",
    )
    if faults is not None:
        from fms_fsdp_tpu.resilience.faults import configure_faults

        configure_faults(faults)
    try:
        mesh = build_mesh(MeshConfig.from_train_config(cfg))
        opt = make_optimizer(cfg)
        state, _ = init_train_state(
            jax.random.PRNGKey(0), model_cfg, cfg, mesh, opt
        )
        step_fn = make_train_step(model_cfg, cfg, mesh, opt)
        n_dp = mesh.shape["replica"] * mesh.shape["fsdp"]
        # 4 fixed batches, cycled — enough signal for a loss trajectory
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, n_dp, 33), 0, 128, dtype=jnp.int32
        )
        losses = []
        for i in range(steps):
            t = toks[i % 4]
            state, metrics = step_fn(state, (t[:, :-1], t[:, 1:]))
            losses.append(float(metrics["loss"]))
        return state, losses
    finally:
        if faults is not None:
            from fms_fsdp_tpu.resilience.faults import configure_faults

            configure_faults("")


# final-loss tolerance vs the bf16 run of the same family. int8's
# 127-step grid tracks closely; e4m3's 3-bit mantissa wanders more; the
# _dgrad modes add backward noise on top.
_MODE_TOL = {
    "int8": 0.08,
    "int8_dgrad": 0.12,
    "fp8": 0.15,
    "fp8_dgrad": 0.20,
}


def _assert_parity(family, mode, tol, base, qs):
    assert np.isfinite(qs).all(), (family, mode)
    delta = abs(qs[-1] - base[-1])
    assert delta < tol, (
        f"{family} {mode}: final loss {qs[-1]:.4f} vs bf16 "
        f"{base[-1]:.4f} (delta {delta:.4f} > tol {tol})"
    )


# The full 5-mode matrices cost ~2-3 min/family on CPU, so they are
# slow-marked to keep local `-m 'not slow'` sweeps inside the tier-1
# budget; CI's dedicated quant-parity step runs this file WITHOUT the
# marker filter, so all three families' matrices are tier-1 in CI.
# Local tier-1 still runs 50-step llama loss parity via the
# quantized-reduce trio below, plus the cross-family smokes.
@pytest.mark.slow
@pytest.mark.parametrize("family", ["llama", "mamba", "mixtral"])
def test_loss_parity_all_modes(family):
    """50-step loss parity: every quantized GEMM mode lands within its
    tolerance of the bf16 trajectory, on all three model families."""
    base = _losses(family, quant="none")
    assert np.isfinite(base).all()
    assert base[-1] < base[0]  # it actually learns
    for mode, tol in _MODE_TOL.items():
        _assert_parity(family, mode, tol, base, _losses(family, quant=mode))


@pytest.mark.parametrize("family", ["mamba", "mixtral"])
def test_fp8_dgrad_trains_cross_family(family):
    """Local-tier-1 cross-family fp8 coverage at smoke depth: the
    strictest mode (fp8_dgrad quantizes BOTH forward and dx) produces
    finite loss on the non-llama families. The full 50-step tolerance
    matrices run in CI's dedicated parity step."""
    _, losses = _run_tiny(family, quant="fp8_dgrad", steps=3)
    assert np.isfinite(losses).all(), (family, losses)


@pytest.mark.parametrize("reduce", ["int8", "fp8", "fp8_delayed"])
def test_loss_parity_quantized_reduce(reduce):
    """The reduce wire formats stay within tolerance of the exact
    reduce on the llama family (the per-row/-leaf scale noise is far
    below gradient noise)."""
    base = _losses("llama", quant="none")
    qs = _losses("llama", reduce=reduce)
    assert np.isfinite(qs).all()
    delta = abs(qs[-1] - base[-1])
    assert delta < 0.10, (reduce, qs[-1], base[-1])


def test_reduce_off_is_bit_identical_and_never_invoked(monkeypatch):
    """quantized_reduce="none" is a pure opt-out: the wire machinery is
    never called (a raising stub proves it), the state carries no quant
    subtree, and the traced program contains no int8/fp8 types."""
    import fms_fsdp_tpu.train.step as step_mod

    def boom(*a, **k):
        raise AssertionError("quantized_grad_reduce invoked with mode none")

    monkeypatch.setattr(step_mod, "quantized_grad_reduce", boom)
    state, losses = _run_tiny("llama", quant="none", reduce="none", steps=3)
    assert "quant" not in state
    assert np.isfinite(losses).all()
    monkeypatch.undo()

    # trace-level pin: no narrow types in the lowered step
    model_cfg = _LLAMA
    cfg = TrainConfig(
        sharding_strategy="fsdp", batch_size=1, seq_length=32,
        num_steps=10, attention_kernel="xla", kernel_tuning="off",
    )
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    state, _ = init_train_state(
        jax.random.PRNGKey(0), model_cfg, cfg, mesh, opt
    )
    step_fn = make_train_step(model_cfg, cfg, mesh, opt)
    n_dp = mesh.shape["replica"] * mesh.shape["fsdp"]
    toks = jnp.zeros((n_dp, 33), jnp.int32)
    hlo = step_fn.lower(state, (toks[:, :-1], toks[:, 1:])).as_text()
    for narrow in ("f8E4M3", "f8E5M2", "xi8>"):
        assert narrow not in hlo, f"{narrow} leaked into the unquantized step"
    # positive control: the quantized builds DO carry the narrow types
    cfg8 = dataclasses.replace(cfg, quantized_matmuls="int8")
    step8 = make_train_step(model_cfg, cfg8, mesh, opt)
    assert "xi8>" in step8.lower(state, (toks[:, :-1], toks[:, 1:])).as_text()
    cfgf = dataclasses.replace(cfg, quantized_reduce="fp8")
    stepf = make_train_step(model_cfg, cfgf, mesh, opt)
    assert "f8E5M2" in stepf.lower(
        state, (toks[:, :-1], toks[:, 1:])
    ).as_text()


def test_delayed_scaling_state_in_train_state():
    """fp8_delayed threads the amax history through the train state:
    present, rolling, and finite after real steps."""
    state, losses = _run_tiny("llama", reduce="fp8_delayed", steps=4)
    assert np.isfinite(losses).all()
    hist = state["quant"]["amax_history"]
    assert hist, "no amax history rows"
    for key, row in hist.items():
        assert key.startswith("g.")
        row = np.asarray(row)
        assert row.dtype == np.float32
        assert np.isfinite(row).all()
    # at least the weight leaves saw nonzero gradients on every step
    nonzero = [np.asarray(r) for r in hist.values() if np.asarray(r)[0] > 0]
    assert nonzero, "no leaf recorded a nonzero amax"
    # 4 steps into a 16-deep window: entries past index 3 still zero
    assert all(float(np.asarray(r)[5]) == 0.0 for r in hist.values())


def test_poisoned_step_does_not_roll_amax():
    """A non-finite batch must not advance the delayed-scaling history
    (NaN in the window would poison every later scale) — the guard
    carries the old window forward like the Adam moments."""
    clean_state, _ = _run_tiny("llama", reduce="fp8_delayed", steps=2)
    poisoned_state, losses = _run_tiny(
        "llama", reduce="fp8_delayed", steps=3,
        faults="nan_loss:step=2:count=1",
    )
    assert not np.isfinite(losses[2])  # the injection fired
    ch = clean_state["quant"]["amax_history"]
    ph = poisoned_state["quant"]["amax_history"]
    for k in ch:
        np.testing.assert_array_equal(np.asarray(ch[k]), np.asarray(ph[k]))
        assert np.isfinite(np.asarray(ph[k])).all()


def test_amax_state_checkpoint_round_trip(tmp_path):
    """The quant subtree checkpoints and restores like optimizer state
    (the fast single-process half of the elastic acceptance; the 2->1
    gloo half lives in tests/test_elastic.py)."""
    from fms_fsdp_tpu.utils.checkpointing import Checkpointer

    state, _ = _run_tiny("llama", reduce="fp8_delayed", steps=3)
    cfg = TrainConfig(
        sharding_strategy="fsdp", batch_size=1, seq_length=32,
        num_steps=10, quantized_reduce="fp8_delayed",
        attention_kernel="xla", kernel_tuning="off",
        ckpt_save_path=str(tmp_path),
    )
    ck = Checkpointer(str(tmp_path), 1, "ddp", 0)
    ck.save(3, state, None)
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    fresh, _ = init_train_state(
        jax.random.PRNGKey(7), _LLAMA, cfg, mesh, opt
    )
    assert "quant" in fresh
    restored, _, start, _, resumed = ck.load(
        fresh, None, path=str(tmp_path / "checkpoints"), strict=False
    )
    assert resumed and start == 3
    for k, row in state["quant"]["amax_history"].items():
        np.testing.assert_array_equal(
            np.asarray(restored["quant"]["amax_history"][k]),
            np.asarray(row),
        )
