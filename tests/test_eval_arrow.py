"""CPU smoke of the chip-evidence eval leg (scripts/chip_evidence.sh
step 4): a REAL arrow corpus through the production data pipeline ->
training entry -> native eval_ppl, asserting perplexity actually falls
vs the fresh-init model on the same stream. This is the
arrow-streaming -> training -> quality connection at tiny scale
(VERDICT r4 #4); the chip script runs the same legs scaled up."""

import pytest

import eval_ppl
import main_training_llama
from fms_fsdp_tpu.data.synth import build_arrow_corpus

TINY = {
    "LlamaConfig.nlayers": 2,
    "LlamaConfig.emb_dim": 64,
    "LlamaConfig.nheads": 4,
    "LlamaConfig.kvheads": 2,
    "LlamaConfig.src_vocab_size": 256,
    "LlamaConfig.multiple_of": 16,
    "LlamaConfig.max_expected_seq_len": 64,
}


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    return build_arrow_corpus(
        tmp_path_factory.mktemp("eval_data"), n_shards=2, docs_per_shard=80
    )


def test_eval_ppl_falls_after_training_on_arrow(corpus, tmp_path):
    data = dict(
        model_variant="llama2_7b",
        data_path=corpus,
        datasets="dataset_1",
        weights="1",
        file_type="arrow",
        vocab_size=256,
        logical_shards=8,
        seq_length=64,
        batch_size=2,
        sharding_strategy="fsdp",
        attention_kernel="xla",
        **TINY,
    )
    # explicit empty load path = fresh-init baseline (the TrainConfig
    # default points at /tmp/output/ckpt, which eval hard-fails on)
    fresh = eval_ppl.main(eval_batches=8, ckpt_load_path="", **data)
    assert fresh["tokens"] > 0

    ckpt = str(tmp_path / "ckpt")
    main_training_llama.main(
        num_steps=80,
        learning_rate=1e-3,
        report_interval=40,
        checkpoint_interval=80,
        ckpt_save_path=ckpt,
        ckpt_load_path=ckpt,
        **data,
    )

    trained = eval_ppl.main(eval_batches=8, ckpt_load_path=ckpt, **data)
    # the corpus is a 90%-deterministic counter chain: even 80 tiny
    # steps must beat the random-init model decisively
    assert trained["ppl"] < 0.9 * fresh["ppl"], (fresh, trained)
