"""Fused lm-head + CE parity tests vs the unfused path and torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from fms_fsdp_tpu.ops.fused_ce import fused_linear_cross_entropy
from fms_fsdp_tpu.train.step import cross_entropy_loss


def _setup(seed=0, b=2, s=9, d=16, v=33):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(b, s, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(d, v)) * 0.1, jnp.float32)
    labels = rng.integers(0, v, size=(b, s))
    labels[0, 0] = -100
    labels[1, 3] = -100
    return x, w, jnp.asarray(labels)


@pytest.mark.parametrize("chunk", [4, 8, 64])
def test_fused_matches_unfused(chunk):
    x, w, labels = _setup()
    ref = cross_entropy_loss(x @ w, labels)
    out = fused_linear_cross_entropy(x, w, labels, chunk)
    assert float(out) == pytest.approx(float(ref), rel=1e-5)


def test_fused_matches_torch():
    x, w, labels = _setup(seed=1)
    out = float(fused_linear_cross_entropy(x, w, labels, 8))
    logits = torch.tensor(np.asarray(x @ w))
    t = float(
        torch.nn.CrossEntropyLoss()(
            logits.view(-1, logits.shape[-1]),
            torch.tensor(np.asarray(labels)).view(-1).long(),
        )
    )
    assert out == pytest.approx(t, rel=1e-5)


@pytest.mark.parametrize("chunk", [4, 64])
def test_fused_grads_match(chunk):
    x, w, labels = _setup(seed=2)

    gf = jax.grad(
        lambda x, w: fused_linear_cross_entropy(x, w, labels, chunk),
        argnums=(0, 1),
    )(x, w)
    gr = jax.grad(
        lambda x, w: cross_entropy_loss(x @ w, labels), argnums=(0, 1)
    )(x, w)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fused_all_ignored():
    x, w, _ = _setup()
    labels = jnp.full((2, 9), -100)
    assert float(fused_linear_cross_entropy(x, w, labels, 8)) == 0.0
    g = jax.grad(lambda x: fused_linear_cross_entropy(x, w, labels, 8))(x)
    assert np.allclose(np.asarray(g), 0)
