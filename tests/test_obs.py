"""Observability subsystem suite (fms_fsdp_tpu/obs/, docs/observability.md):
registry semantics, phase-timer math under a fake clock, goodput folding
in resilience skipped steps, JSONL/CSV sink schema round-trips, the
heartbeat contract, the schema-version digest guard, and an e2e CPU
smoke asserting a tiny fault-injected run writes a parseable
metrics.jsonl whose goodput reflects the skipped step — while the
ref-exact print report stays byte-identical in shape."""

import json
import os

import pytest

from fms_fsdp_tpu.obs.observer import Observer, build_observer
from fms_fsdp_tpu.obs.registry import MetricRegistry
from fms_fsdp_tpu.obs.schema import (
    SCHEMA_DIGESTS,
    SCHEMA_VERSION,
    schema_digest,
    validate_record,
)
from fms_fsdp_tpu.obs.sinks import (
    CSVSink,
    Heartbeat,
    JSONLSink,
    TrackerSink,
    build_sinks,
    read_heartbeat,
)
from fms_fsdp_tpu.obs.timing import GoodputTracker, PhaseTimer

TINY_OVERRIDES = {
    "LlamaConfig.nlayers": 2,
    "LlamaConfig.emb_dim": 64,
    "LlamaConfig.nheads": 4,
    "LlamaConfig.kvheads": 2,
    "LlamaConfig.src_vocab_size": 256,
    "LlamaConfig.multiple_of": 16,
    "LlamaConfig.max_expected_seq_len": 64,
}


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


# ---- registry --------------------------------------------------------------


def test_registry_counter_cumulative_and_window():
    reg = MetricRegistry()
    reg.counter("c").add(2)
    reg.counter("c").add(3)
    snap = reg.snapshot()
    assert snap["c"] == 5 and snap["c_window"] == 5
    reg.counter("c").add(1)
    snap = reg.snapshot()
    assert snap["c"] == 6 and snap["c_window"] == 1
    # idempotent identity: counter(name) returns the same cell
    assert reg.counter("c") is reg.counter("c")


def test_registry_gauge_ewma_hist():
    reg = MetricRegistry()
    reg.gauge("g").set(7.5)
    reg.ewma("e", alpha=0.5).update(1.0)
    reg.ewma("e").update(3.0)
    for v in [1.0, 2.0, 3.0, 4.0]:
        reg.hist("h").record(v)
    snap = reg.snapshot()
    assert snap["g"] == 7.5
    assert snap["e"] == pytest.approx(2.0)  # 0.5*3 + 0.5*1
    assert snap["h_mean"] == pytest.approx(2.5)
    assert snap["h_max"] == 4.0
    # window cleared: next snapshot has no h stats
    assert "h_mean" not in reg.snapshot()
    # empty registry snapshots cleanly
    assert MetricRegistry().snapshot() == {}


# ---- phase timer (fake clock) ----------------------------------------------


def test_phase_timer_attribution_and_other():
    clk = FakeClock()
    t = PhaseTimer(clock=clk)
    with t.phase("data_wait"):
        clk.tick(2.0)
    with t.phase("compute"):
        clk.tick(5.0)
    clk.tick(3.0)  # unattributed -> other
    w = t.window()
    assert w["data_wait"] == pytest.approx(2.0)
    assert w["compute"] == pytest.approx(5.0)
    assert w["checkpoint"] == 0.0
    assert w["other"] == pytest.approx(3.0)
    assert w["wall"] == pytest.approx(10.0)
    # window reset: a fresh window starts from zero
    clk.tick(1.0)
    w2 = t.window()
    assert w2["compute"] == 0.0 and w2["wall"] == pytest.approx(1.0)


def test_phase_timer_nested_inner_wins():
    clk = FakeClock()
    t = PhaseTimer(clock=clk)
    with t.phase("compute"):
        clk.tick(1.0)
        with t.phase("checkpoint"):
            clk.tick(10.0)
        clk.tick(2.0)
    w = t.window()
    assert w["compute"] == pytest.approx(3.0)
    assert w["checkpoint"] == pytest.approx(10.0)
    assert w["wall"] == pytest.approx(13.0)


def test_phase_timer_record_direct():
    t = PhaseTimer(clock=FakeClock())
    t.record("data_wait", 1.25)
    assert t.window()["data_wait"] == pytest.approx(1.25)


# ---- goodput ---------------------------------------------------------------


def test_goodput_clean_window():
    g = GoodputTracker()
    win, overall = g.update(
        {"wall": 10.0, "compute": 8.0}, steps=4, skipped_steps=0
    )
    assert win == pytest.approx(0.8)
    assert overall == pytest.approx(0.8)


def test_goodput_folds_skipped_steps():
    g = GoodputTracker()
    # 4 steps, 1 skipped: only 3/4 of the compute time was productive
    win, _ = g.update({"wall": 10.0, "compute": 8.0}, steps=4, skipped_steps=1)
    assert win == pytest.approx(8.0 * 0.75 / 10.0)
    # cumulative: a later clean window lifts the overall number
    _, overall = g.update(
        {"wall": 10.0, "compute": 8.0}, steps=4, skipped_steps=0
    )
    assert overall == pytest.approx((6.0 + 8.0) / 20.0)


def test_goodput_zero_wall_no_crash():
    win, overall = GoodputTracker().update(
        {"wall": 0.0, "compute": 0.0}, steps=1
    )
    assert win == 0.0 and overall == 0.0


# ---- schema ----------------------------------------------------------------


def test_schema_digest_pins_version():
    """Changing SCHEMA_FIELDS without bumping SCHEMA_VERSION fails here
    (and in CI). To evolve the schema: bump the version, pin the new
    digest (printed below), document in docs/observability.md."""
    assert SCHEMA_VERSION in SCHEMA_DIGESTS, "pin a digest for this version"
    assert schema_digest() == SCHEMA_DIGESTS[SCHEMA_VERSION], (
        f"metric schema changed without a version bump; new digest: "
        f"{schema_digest()}"
    )


def test_validate_record_catches_violations():
    good = _observer_record()
    assert validate_record(good) == []
    bad = dict(good)
    bad.pop("goodput")
    assert any("goodput" in e for e in validate_record(bad))
    bad = dict(good, loss="high")
    assert any("loss" in e for e in validate_record(bad))
    bad = dict(good, surprise=1)
    assert any("surprise" in e for e in validate_record(bad))
    bad = dict(good, schema_version=SCHEMA_VERSION + 1)
    assert any("schema_version" in e for e in validate_record(bad))


def _observer_record(**kw):
    obs = Observer(clock=FakeClock(), strict_schema=True)
    args = dict(
        loss=2.5,
        tokens_per_sec_per_chip=1000.0,
        skipped_steps_total=0,
        skipped_steps_window=0,
    )
    args.update(kw)
    return obs.report(10, 4, **args)


def test_quant_modes_land_in_record():
    """schema v4: the step's quantization modes ride every record; a
    perf record must state the numerics that produced it. Built from
    config via build_observer, null when unset."""
    rec = _observer_record()
    assert rec["quantized_matmuls"] is None
    assert rec["quantized_reduce"] is None

    from fms_fsdp_tpu.obs import build_observer

    class Cfg:
        obs_dir = ""
        obs_sinks = ""
        kernel_tuning = "auto"
        quantized_matmuls = "int8_dgrad"
        quantized_reduce = "fp8_delayed"
        seq_length = 64

    obs = build_observer(Cfg(), rank=0, clock=FakeClock())
    rec = obs.report(
        10,
        4,
        loss=2.5,
        tokens_per_sec_per_chip=1000.0,
        skipped_steps_total=0,
        skipped_steps_window=0,
    )
    assert rec["quantized_matmuls"] == "int8_dgrad"
    assert rec["quantized_reduce"] == "fp8_delayed"
    assert validate_record(rec) == []


def test_checkpoint_stats_provider_feeds_record():
    """schema v2: the async checkpoint manager's stats provider fills
    checkpoint_bg_s / checkpoint_in_flight; without a provider both
    default to zero (plain synchronous Checkpointer)."""
    rec = _observer_record()
    assert rec["checkpoint_bg_s"] == 0.0
    assert rec["checkpoint_in_flight"] == 0

    obs = Observer(clock=FakeClock(), strict_schema=True)
    obs.attach_checkpoint_stats(lambda: {"bg_s": 3.5, "in_flight": 1})
    rec = obs.report(
        10,
        4,
        loss=2.5,
        tokens_per_sec_per_chip=1000.0,
        skipped_steps_total=0,
        skipped_steps_window=0,
    )
    assert rec["checkpoint_bg_s"] == pytest.approx(3.5)
    assert rec["checkpoint_in_flight"] == 1
    assert validate_record(rec) == []


# ---- observer --------------------------------------------------------------


def test_observer_report_derives_mfu_and_goodput():
    clk = FakeClock()
    obs = Observer(
        clock=clk,
        flops_per_token=100.0,
        hfu_flops_per_token=120.0,
        peak_flops=1e6,
        strict_schema=True,
    )
    with obs.phase("compute"):
        clk.tick(8.0)
    clk.tick(2.0)
    rec = obs.report(
        5,
        4,
        loss=2.0,
        tokens_per_sec_per_chip=5000.0,
        skipped_steps_total=1,
        skipped_steps_window=1,
    )
    assert validate_record(rec) == []
    assert rec["mfu"] == pytest.approx(0.5)
    assert rec["hfu"] == pytest.approx(0.6)
    assert rec["goodput"] == pytest.approx(8.0 * 0.75 / 10.0)
    assert rec["wall_s"] == pytest.approx(10.0)
    assert rec["skipped_steps"] == 1


def test_observer_wrap_data_iter_times_waits():
    clk = FakeClock()
    obs = Observer(clock=clk)

    def gen():
        for i in range(3):
            clk.tick(1.0)  # "the pipeline is slow"
            yield i

    assert list(obs.wrap_data_iter(gen())) == [0, 1, 2]
    assert obs.timer.window()["data_wait"] == pytest.approx(3.0)


def test_observer_registry_lands_in_extra(tmp_path):
    obs = Observer(
        sinks=[JSONLSink(str(tmp_path / "m.jsonl"))], clock=FakeClock()
    )
    obs.registry.counter("feed.batches").add(7)
    obs.report(
        1, 1, loss=1.0, tokens_per_sec_per_chip=1.0,
        skipped_steps_total=0, skipped_steps_window=0,
    )
    rec = json.loads((tmp_path / "m.jsonl").read_text())
    assert rec["extra"]["feed.batches"] == 7


def test_observer_nonfinite_window_emits_null_not_nan(tmp_path):
    """A fully-poisoned window (NaN loss/gnorm) must serialize as null —
    a bare NaN token would make the JSONL line unparseable by strict
    parsers exactly in the fault window the record exists to capture."""
    obs = Observer(
        sinks=[JSONLSink(str(tmp_path / "m.jsonl"))],
        clock=FakeClock(),
        strict_schema=True,
    )
    obs.registry.gauge("bad").set(float("inf"))
    rec = obs.report(
        2, 2,
        loss=float("nan"),
        grad_norm=float("nan"),
        tokens_per_sec_per_chip=100.0,
        skipped_steps_total=2,
        skipped_steps_window=2,
    )
    assert rec["loss"] is None and rec["grad_norm"] is None
    assert rec["extra"]["bad"] is None
    line = (tmp_path / "m.jsonl").read_text()
    assert "NaN" not in line and "Infinity" not in line
    parsed = json.loads(line)  # strict parse round-trips
    assert validate_record(parsed) == []
    assert parsed["skipped_steps_window"] == 2


# ---- sinks -----------------------------------------------------------------


def test_jsonl_sink_roundtrip_validates(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    sink = JSONLSink(path)
    for step in (2, 4):
        sink.emit(_observer_record())
    sink.close()
    lines = open(path).read().splitlines()
    assert len(lines) == 2
    for ln in lines:
        assert validate_record(json.loads(ln)) == []


def test_csv_sink_columns_and_append(tmp_path):
    path = str(tmp_path / "metrics.csv")
    sink = CSVSink(path)
    sink.emit(_observer_record())
    sink.emit(_observer_record())
    sink.close()
    lines = open(path).read().splitlines()
    assert lines[0].startswith("schema_version,step,")
    assert "extra" not in lines[0]
    assert len(lines) == 3
    # append after reopen: no duplicate header
    sink2 = CSVSink(path)
    sink2.emit(_observer_record())
    sink2.close()
    assert len(open(path).read().splitlines()) == 4


def test_tracker_sink_emits_legacy_keys():
    logged = []
    TrackerSink(lambda d, step: logged.append((d, step))).emit(
        _observer_record()
    )
    (payload, step), = logged
    assert step == 10
    # the exact key names the pre-obs loop logged (dashboards key on them)
    for key in (
        "learning rate", "loss", "gradient norm", "token seen",
        "current throughput (token per chip per sec)",
        "overall throughput (token per chip per sec)",
        "chip reserved memory", "chip allocated memory", "skipped batches",
    ):
        assert key in payload, key


def test_tracker_sink_disables_on_backend_error():
    """A raising tracker backend (finished wandb run, aim db error) must
    disable the sink, never propagate into the hot loop."""
    calls = []

    def flaky(d, step):
        calls.append(step)
        raise RuntimeError("wandb run finished")

    sink = TrackerSink(flaky)
    sink.emit(_observer_record())  # must not raise
    assert sink._broken
    sink.emit(_observer_record())  # disabled: backend not called again
    assert len(calls) == 1


def test_heartbeat_contract(tmp_path):
    path = str(tmp_path / "hb" / "heartbeat.json")
    Heartbeat(path).beat(42, 1234.5, 0.875)
    hb = read_heartbeat(path)
    assert hb == {
        "step": 42,
        "time_unix": 1234.5,
        "goodput": 0.875,
        "schema_version": SCHEMA_VERSION,
    }
    assert read_heartbeat(str(tmp_path / "nope.json")) is None


def test_sink_io_error_disables_not_raises(tmp_path, monkeypatch):
    sink = JSONLSink(str(tmp_path / "m.jsonl"))
    sink.emit(_observer_record())

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(sink._f, "write", boom)
    sink.emit(_observer_record())  # must not raise
    assert sink._broken
    sink.emit(_observer_record())  # still silent


def test_build_sinks_unknown_name_raises(tmp_path):
    with pytest.raises(ValueError, match="unknown obs sink"):
        build_sinks(str(tmp_path), ["jsonl", "speedometer"])
    # jsonl/csv need a dir; tracker needs a fn — silently absent otherwise
    assert build_sinks("", ["jsonl", "csv", "tracker"]) == []


def test_build_observer_rank_gating(tmp_path):
    from fms_fsdp_tpu.config import TrainConfig

    cfg = TrainConfig(obs_dir=str(tmp_path / "obs"), obs_sinks="jsonl,csv")
    obs0 = build_observer(cfg, rank=0)
    obs1 = build_observer(cfg, rank=1)
    assert len(obs0.sinks) == 2 and obs0.heartbeat is not None
    assert obs1.sinks == [] and obs1.heartbeat is None


def test_build_observer_flops_model():
    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.utils.config_utils import get_model_config

    model_cfg = get_model_config("llama3_194m_4k")
    cfg = TrainConfig(
        seq_length=128,
        fsdp_activation_checkpointing=True,
        selective_checkpointing=0.5,
    )
    obs = build_observer(cfg, rank=0, model_cfg=model_cfg)
    assert obs.flops_per_token and obs.peak_flops
    # HFU numerator counts the recompute: strictly above the MFU one
    assert obs.hfu_flops_per_token > obs.flops_per_token


def test_device_feed_finite_loader_terminates():
    """A finite loader behind a prefetching DeviceFeed must end the
    consumer's iteration (sentinel on clean exhaustion), not leave it
    blocked in q.get() forever — and the feed counters land in the
    registry."""
    import numpy as np

    from fms_fsdp_tpu.data.device_feed import DeviceFeed
    from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(sharding_strategy="fsdp"))
    reg = MetricRegistry()
    loader = iter([np.zeros((2, 8), np.int32)] * 3)
    feed = DeviceFeed(loader, mesh, prefetch=2, registry=reg)
    batches = list(feed)  # hangs without the StopIteration sentinel
    assert len(batches) == 3
    assert reg.snapshot()["feed.batches"] == 3


# ---- watchdog x heartbeat --------------------------------------------------


def test_watchdog_stall_report_quotes_heartbeat(tmp_path):
    """A stalled run's watchdog post-mortem includes the last heartbeat
    (how far the run got, how healthy it was) before exiting 2 — and
    every report line carries the host's process index (passed in at
    construction, never fetched from jax on the wedged-process path) so
    merged multi-host logs attribute WHICH host's stacks follow."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hb_path = str(tmp_path / "heartbeat.json")
    script = (
        "import time, sys\n"
        "sys.path.insert(0, %r)\n"
        "from fms_fsdp_tpu.obs.sinks import Heartbeat\n"
        "from fms_fsdp_tpu.resilience.guards import StepWatchdog\n"
        "Heartbeat(%r).beat(123, 99.0, 0.5)\n"
        "w = StepWatchdog(0.5, heartbeat_path=%r, process_index=3).start()\n"
        "w.beat()\n"
        "time.sleep(30)\n"
    ) % (repo, hb_path, hb_path)
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 2, (proc.returncode, proc.stderr[-1000:])
    assert "step watchdog [proc 3]: no training progress" in proc.stderr, (
        proc.stderr[-1000:]
    )
    assert "step watchdog [proc 3]: last heartbeat" in proc.stderr, (
        proc.stderr[-1000:]
    )
    assert "'step': 123" in proc.stderr, proc.stderr[-1000:]


# ---- hot-loop accounting (drives _train_loop with fakes) -------------------


class _CaptureSink:
    def __init__(self):
        self.records = []

    def emit(self, rec):
        self.records.append(rec)

    def close(self):
        pass


class _FakeCheckpointer:
    observer = None

    def __init__(self):
        self.saves = []

    def save(self, step, state, dataloader=None, reason="interval", **md):
        self.saves.append((step, reason, md))

    def finalize(self):
        pass


def _drive_loop(
    num_steps,
    report_interval,
    nonfinite_steps=(),
    start_step=0,
    step_sleep=0.0,
    checkpoint_interval=10**9,
):
    """Run the real _train_loop over a fake step_fn/loader/checkpointer;
    metrics are host floats so the report-time device_get is a no-op."""
    import time as _time

    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.utils.train_utils import _train_loop

    cfg = TrainConfig(
        num_steps=num_steps,
        report_interval=report_interval,
        checkpoint_interval=checkpoint_interval,
        batch_size=2,
        seq_length=8,
        step_timeout_s=0,
    )
    cap = _CaptureSink()
    obs = Observer(sinks=[cap])
    ck = _FakeCheckpointer()

    def step_fn(state, batch):
        if step_sleep:
            _time.sleep(step_sleep)
        i = state["i"] + 1
        bad = i in nonfinite_steps
        return dict(state, i=i), {
            "loss": float("nan") if bad else 2.0 + i * 0.01,
            "gnorm": float("nan") if bad else 1.0,
            "lr": 0.1,
            "nonfinite": 1.0 if bad else 0.0,
        }

    loss = _train_loop(
        cfg,
        {"i": start_step},
        step_fn,
        0,
        iter(int, 1),  # infinite stream of dummy batches
        None,
        ck,
        start_step,
        0,
        obs,
        1,
    )
    return loss, cap.records, ck


def test_train_loop_partial_window_rates_use_true_step_count():
    """A resume's first report window is partial (len(fetched) <
    report_interval): the record's step_time_s / throughput must divide
    by the TRUE step count, not the configured interval — else a resume
    inflates the persistent throughput/MFU record 2x here."""
    per_step = 0.05
    loss, records, _ = _drive_loop(
        num_steps=4, report_interval=4, start_step=2, step_sleep=per_step
    )
    assert [r["step"] for r in records] == [4]
    rec = records[0]
    # two steps of >= 50ms each: a report_interval divisor would halve it
    assert rec["step_time_s"] >= per_step * 0.9, rec["step_time_s"]
    # rate and step time stay algebraically consistent with batch tokens
    assert rec["tokens_per_sec_per_chip"] * rec["step_time_s"] == pytest.approx(
        2 * 8
    )


def test_train_loop_drains_tail_window_on_exit():
    """num_steps lands mid-report-window: the tail steps' non-finite
    flags must still reach the guard (skipped_steps in the final record)
    and the final save's metadata — not vanish with the undrained
    window."""
    loss, records, ck = _drive_loop(
        num_steps=6, report_interval=4, nonfinite_steps={6}
    )
    assert [r["step"] for r in records] == [4, 6]
    tail = records[-1]
    assert tail["skipped_steps_window"] == 1
    assert tail["skipped_steps"] == 1
    # the drained window still carries its clean step's loss
    assert tail["loss"] == pytest.approx(2.0 + 5 * 0.01)
    # the final save's metadata records the guard's totals
    steps = [s for s in ck.saves if s[1] == "final"]
    assert steps and steps[-1][2]["skipped_steps"] == 1
    # exact tokens at the save step, not the last report's stale figure
    assert steps[-1][2]["tokens_seen"] == 6 * 2 * 8


def test_train_loop_poisoned_window_carries_last_clean_loss(capsys):
    """Every step of a window non-finite: the window is reported as
    poisoned — the record's loss is null (never NaN into sinks), the
    print stream carries the last clean loss, and the returned loss is
    the carried one."""
    loss, records, _ = _drive_loop(
        num_steps=4, report_interval=2, nonfinite_steps={3, 4}
    )
    out = capsys.readouterr().out
    assert "report window poisoned: all 2 step(s) non-finite" in out
    clean, poisoned = records
    assert clean["loss"] is not None
    assert poisoned["loss"] is None
    assert poisoned["grad_norm"] is None
    assert poisoned["skipped_steps_window"] == 2
    assert poisoned["extra"].get("window_poisoned") == 1
    # carried: the last clean window's mean, also the returned loss
    assert loss == pytest.approx(clean["loss"])


# ---- e2e CPU smoke ---------------------------------------------------------


@pytest.mark.slow
def test_e2e_metrics_jsonl_with_injected_skip(tmp_path, capsys):
    """Tiny fault-injected llama run: every metrics.jsonl line validates
    against the documented schema, carries loss / tokens-per-sec / MFU /
    data-wait fraction / goodput, the skipped step depresses its
    window's goodput, the heartbeat tracks the last step, and the
    ref-exact print lines keep their exact shape."""
    import main_training_llama

    obs_dir = tmp_path / "obs"
    main_training_llama.main(
        use_dummy_dataset=True,
        num_steps=6,
        seq_length=32,
        batch_size=2,
        report_interval=2,
        checkpoint_interval=100,
        vocab_size=256,
        sharding_strategy="fsdp",
        attention_kernel="xla",
        ckpt_save_path=str(tmp_path),
        ckpt_load_path=str(tmp_path),
        obs_dir=str(obs_dir),
        obs_sinks="jsonl,csv",
        obs_strict_schema=True,
        faults="nan_loss:step=2:count=1",
        **TINY_OVERRIDES,
    )
    out = capsys.readouterr().out

    records = [
        json.loads(ln)
        for ln in (obs_dir / "metrics.jsonl").read_text().splitlines()
    ]
    assert len(records) == 3  # 6 steps / report_interval 2
    for rec in records:
        assert validate_record(rec) == [], rec
        for field in (
            "loss", "tokens_per_sec_per_chip", "mfu",
            "data_wait_frac", "goodput",
        ):
            assert rec[field] is not None
    # the injected NaN batch (device step counter 2 -> trainer step 3,
    # the second report window) is folded into that window's accounting
    assert records[0]["skipped_steps_window"] == 0
    assert records[1]["skipped_steps_window"] == 1
    assert records[1]["skipped_steps"] == 1
    assert records[-1]["skipped_steps"] == 1
    # goodput < 1 and consistent with its own phase decomposition: the
    # skipped step halves the window's productive compute time
    w = records[1]
    assert 0.0 <= w["goodput"] <= 1.0
    expected = (w["compute_s"] * (2 - 1) / 2) / w["wall_s"]
    assert w["goodput"] == pytest.approx(expected, rel=1e-6)
    clean = records[0]
    assert clean["goodput"] == pytest.approx(
        clean["compute_s"] / clean["wall_s"], rel=1e-6
    )

    # heartbeat tracks the last report step
    hb = read_heartbeat(str(obs_dir / "heartbeat.json"))
    assert hb["step"] == 6
    assert hb["goodput"] == pytest.approx(records[-1]["goodput"])

    # CSV summary has header + one row per report
    assert len((obs_dir / "metrics.csv").read_text().splitlines()) == 4

    # ref-exact print report: same labels, same order, every window
    labels = [
        "step:", "loss:", "LR:", "tokens seen:", "gradient norm:",
        "reserved memory:", "allocated memory:", "current step time:",
        "overall step time:", "current token per chip per sec:",
        "overall token per chip per sec:", "overall token per day:",
    ]
    printed = [
        ln for ln in out.splitlines()
        if any(ln.startswith(lbl) for lbl in labels)
    ]
    assert len(printed) == 3 * len(labels), out[-2000:]
    assert "skipped batches: 1" in out
    # no obs chatter leaked into the report stream: no line *starts*
    # with an unknown label (the obs layer prints nothing of its own)
    known = tuple(labels) + (
        "-->", "Sharding strategy", "Constructing", "Datasets", "No valid",
        "Training for", "skipped batches:", "Checkpoint saved",
        "model_save_time",
    )
    for ln in out.splitlines():
        if ln.strip():
            assert ln.startswith(known), f"unexpected output line: {ln!r}"


@pytest.mark.slow
def test_e2e_observer_absent_obs_dir_writes_nothing(tmp_path, capsys):
    """Default config (obs_dir=""): no metrics files appear anywhere and
    the run is byte-compatible with the pre-obs loop."""
    import main_training_llama

    main_training_llama.main(
        use_dummy_dataset=True,
        num_steps=2,
        seq_length=32,
        batch_size=2,
        report_interval=2,
        checkpoint_interval=100,
        vocab_size=256,
        sharding_strategy="fsdp",
        attention_kernel="xla",
        ckpt_save_path=str(tmp_path),
        ckpt_load_path=str(tmp_path),
        **TINY_OVERRIDES,
    )
    capsys.readouterr()
    found = [
        os.path.join(r, f)
        for r, _, fs in os.walk(tmp_path)
        for f in fs
        if f in ("metrics.jsonl", "metrics.csv", "heartbeat.json")
    ]
    assert found == []


def test_v5_collective_split_defaults_zero():
    """schema v5: single-slice runs (no probe attached) report 0.0 for
    both collective-split fields — and the record still validates."""
    rec = _observer_record()
    assert rec["ici_collective_s"] == 0.0
    assert rec["dcn_collective_s"] == 0.0
    assert validate_record(rec) == []


def test_collective_probe_fills_v5_split():
    """On a multi-slice mesh the report-cadence probe (obs/collectives)
    times a real within-slice and a real cross-slice reduction into the
    v5 fields; on a single-slice mesh no probe exists at all."""
    from fms_fsdp_tpu.obs.collectives import make_collective_split_probe
    from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh

    single = build_mesh(MeshConfig(sharding_strategy="fsdp"))
    obs = Observer(strict_schema=True)
    assert make_collective_split_probe(single, obs.timer) is None

    mesh = build_mesh(MeshConfig(sharding_strategy="fsdp", num_slices=2))
    probe = make_collective_split_probe(mesh, obs.timer)
    assert probe is not None
    obs.attach_collective_probe(probe)
    rec = obs.report(
        10,
        4,
        loss=2.5,
        tokens_per_sec_per_chip=1000.0,
        skipped_steps_total=0,
        skipped_steps_window=0,
    )
    assert rec["ici_collective_s"] > 0.0, rec
    assert rec["dcn_collective_s"] > 0.0, rec
    assert validate_record(rec) == []
