"""Elastic resume: survive preemption and restart on a different topology.

Three layers of coverage (docs/checkpointing.md "Elastic resume"):

- fingerprint contract: every checkpoint's metadata.json carries the
  save-time topology; the load gate validates rescale legality BEFORE
  any collective restore, with actionable errors (and a pinned digest so
  the field set can't drift silently);
- data layer: a mid-epoch save at world 2 restores at world 1 and 4 with
  the global document walk a seamless continuation — every document of
  the epoch seen exactly once across the boundary (no replay, no skip);
- e2e (slow, gloo multi-process — pattern from test_multiprocess.py):
  train at world=2 over real arrow data, save (including a kill mid
  async commit via the ckpt_precommit_kill fault site), resume at
  world=1 and world=4 — params restore bit-identically onto the new
  mesh (topology-independent state hash), the global batch is preserved
  (per-rank rows recomputed), and the trainer-consumed document stream
  never replays a document across the boundary;
- multi-slice fault domains (docs/resilience.md): the check_rescale
  slice matrix (loss/gain legal, changed per-slice shape illegal,
  legacy v1 fingerprints load with a note) plus the slow 2-slice x
  2-host gloo e2e — slice 1 killed whole mid-run, survivors fail-fast
  with the classified fault-domain report, and the restart at world
  minus one fault domain resumes bit-identically with zero replays.
"""

import os
import signal
import socket
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_elastic_child.py")

MARKER_BASE = 1024  # keep in sync with tests/_elastic_child.py


# ---- fingerprint contract --------------------------------------------------


def _fp(**over):
    fp = {
        "process_count": 2,
        "device_count": 8,
        "tensor_parallel_size": 1,
        "context_parallel_size": 1,
        "global_batch_rows": 16,
        "seq_length": 64,
        "n_logical_shards": 8,
        "loader_files": 2,
        "num_slices": 1,
        "slice_process_count": 2,
        "slice_device_count": 8,
        "corpus_names": "dataset_1,dataset_2",
        "mix_weights_digest": "aaaa1111bbbb2222",
    }
    fp.update(over)
    return fp


def _slice_fp(n_slices, spc=2, sdc=8, **over):
    """A multi-slice fingerprint: n_slices fault domains of spc
    processes x sdc devices, one loader worker per process."""
    fp = _fp(
        num_slices=n_slices,
        slice_process_count=spc,
        slice_device_count=sdc,
        process_count=n_slices * spc,
        device_count=n_slices * sdc,
        loader_files=n_slices * spc,
    )
    fp.update(over)
    return fp


def test_topology_digest_pinned():
    """The fingerprint field set is a cross-run contract (old
    checkpoints are read by new code): changing it must bump
    TOPOLOGY_VERSION and pin the new digest — same guard as the obs
    metric schema."""
    from fms_fsdp_tpu.ckpt.elastic import (
        TOPOLOGY_DIGESTS,
        TOPOLOGY_VERSION,
        topology_digest,
    )

    assert TOPOLOGY_DIGESTS.get(TOPOLOGY_VERSION) == topology_digest(), (
        f"topology fingerprint changed without a version bump: pin "
        f"{topology_digest()} for version {TOPOLOGY_VERSION}"
    )


def test_check_rescale_same_topology_is_noop():
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    problems, changed = check_rescale(_fp(), _fp())
    assert problems == [] and changed is False


def test_check_rescale_legal_change_detected():
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    # 2 hosts -> 1 host, global batch preserved, loader world divides
    new = _fp(process_count=1, device_count=4, loader_files=1)
    problems, changed = check_rescale(_fp(), new)
    assert problems == [] and changed is True


def test_check_rescale_nondividing_loader_world():
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    new = _fp(process_count=3, device_count=12, loader_files=3)
    problems, _ = check_rescale(_fp(), new, allow_batch_change=True)
    assert any("does not divide n_logical_shards" in p for p in problems)


def test_check_rescale_changed_logical_shards():
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    problems, _ = check_rescale(_fp(), _fp(n_logical_shards=6))
    assert any("--logical_shards=8" in p for p in problems)


def test_check_rescale_batch_change_needs_flag():
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    new = _fp(global_batch_rows=8)
    problems, _ = check_rescale(_fp(), new)
    assert any("allow_batch_change" in p for p in problems)
    problems, changed = check_rescale(_fp(), new, allow_batch_change=True)
    assert problems == [] and changed is True


def test_check_rescale_mixing_legality_matrix():
    """The v3 data-mix legality matrix: corpus-SET changes are gated
    (state pairs by name and cannot follow added/removed corpora),
    reorders and weight changes are legal — the latter two produce the
    describe_mixing_change note the gate prints."""
    from fms_fsdp_tpu.ckpt.elastic import (
        check_rescale,
        describe_mixing_change,
    )

    # corpus removed: actionable problem naming both escape hatches
    new = _fp(corpus_names="dataset_1")
    problems, _ = check_rescale(_fp(), new)
    assert any("corpus set changed" in p for p in problems)
    assert any("--datasets=dataset_1,dataset_2" in p for p in problems)
    assert any("allow_corpus_change" in p for p in problems)
    # ...accepted with the escape hatch
    problems, changed = check_rescale(_fp(), new, allow_corpus_change=True)
    assert problems == [] and changed is True

    # corpus added: gated the same way
    problems, _ = check_rescale(
        _fp(), _fp(corpus_names="dataset_1,dataset_2,dataset_3")
    )
    assert any("corpus set changed" in p for p in problems)

    # pure reorder: legal, note names the name-keyed pairing
    reordered = _fp(corpus_names="dataset_2,dataset_1")
    problems, changed = check_rescale(_fp(), reordered)
    assert problems == [] and changed is True
    note = describe_mixing_change(_fp(), reordered)
    assert note and "pairs by name" in note

    # weight change: legal, note says the controller re-steers
    reweighted = _fp(mix_weights_digest="cccc3333dddd4444")
    problems, changed = check_rescale(_fp(), reweighted)
    assert problems == [] and changed is True
    note = describe_mixing_change(_fp(), reweighted)
    assert note and "weights changed" in note

    # unchanged mix: no note
    assert describe_mixing_change(_fp(), _fp()) is None


def test_check_rescale_pre_v3_fingerprint_skips_mixing_checks():
    """Pre-v3 fingerprints carry no mix fields: the mixing checks treat
    them as wildcard (the load gate's version note still prints)."""
    from fms_fsdp_tpu.ckpt.elastic import check_rescale, describe_mixing_change

    v2 = {
        k: v
        for k, v in _fp().items()
        if k not in ("corpus_names", "mix_weights_digest")
    }
    problems, changed = check_rescale(
        v2, _fp(corpus_names="brand,new,set")
    )
    assert problems == [] and changed is True
    assert describe_mixing_change(v2, _fp()) is None


def test_mixing_fingerprint_from_config():
    """current_fingerprint derives the mix dims from cfg.datasets /
    cfg.weights; dummy-data runs fingerprint as empty (wildcard)."""
    from fms_fsdp_tpu.ckpt.elastic import mixing_fingerprint
    from fms_fsdp_tpu.config import TrainConfig

    cfg = TrainConfig(datasets="a,b,c", weights="2,1,1")
    names, digest = mixing_fingerprint(cfg)
    assert names == "a,b,c" and len(digest) == 16
    # weight digest is scale-invariant (normalized) but order-sensitive
    assert mixing_fingerprint(
        TrainConfig(datasets="a,b,c", weights="4,2,2")
    ) == (names, digest)
    assert mixing_fingerprint(
        TrainConfig(datasets="a,b,c", weights="1,2,1")
    )[1] != digest
    assert mixing_fingerprint(
        TrainConfig(use_dummy_dataset=True)
    ) == ("", "")


def test_check_rescale_slice_loss_is_legal():
    """Losing a fault domain (3 -> 2 slices, per-slice shape unchanged,
    global batch preserved) is a legal elastic rescale."""
    from fms_fsdp_tpu.ckpt.elastic import check_rescale, describe_change

    old, new = _slice_fp(3), _slice_fp(2)
    problems, changed = check_rescale(old, new)
    assert problems == [] and changed is True
    assert "num_slices: 3 -> 2" in describe_change(old, new)


def test_check_rescale_slice_gain_is_legal():
    """Capacity coming BACK (2 -> 4 slices of the same shape) is just as
    legal — elastic both directions. (The slice count must still satisfy
    the ordinary loader rule: the new process x worker product divides
    n_logical_shards — 3 slices x 2 workers over 8 shards would fail
    THAT check, not a slice check.)"""
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    problems, changed = check_rescale(_slice_fp(2), _slice_fp(4))
    assert problems == [] and changed is True
    problems, _ = check_rescale(_slice_fp(2), _slice_fp(3))
    assert problems and all("n_logical_shards" in p for p in problems)


def test_check_rescale_changed_per_slice_shape_illegal():
    """While both worlds are multi-slice the per-slice shape is pinned:
    the error is actionable (restart with matching slices, or as a
    single slice)."""
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    problems, _ = check_rescale(_slice_fp(2), _slice_fp(2, spc=1, sdc=4))
    assert any("slice_process_count changed" in p for p in problems)
    assert any("slice_device_count changed" in p for p in problems)
    assert any("fault domain" in p for p in problems)
    assert any("--num_slices=1" in p for p in problems)


def test_check_rescale_multislice_to_single_slice_legal():
    """The acceptance path: a 2-slice world loses a slice and restarts
    single-slice on the survivor's shape — legal, governed only by the
    ordinary batch/loader rules (the per-slice pin applies only while
    BOTH sides are multi-slice)."""
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    old = _slice_fp(2)  # 4 procs, 16 devices, loader_files=4
    new = _fp(
        num_slices=1,
        process_count=2,
        device_count=8,
        slice_process_count=2,
        slice_device_count=8,
        loader_files=2,
    )
    problems, changed = check_rescale(old, new)
    assert problems == [] and changed is True
    # ...and so is a single-slice restart on a DIFFERENT shape
    odd = _fp(
        num_slices=1,
        process_count=4,
        device_count=16,
        slice_process_count=4,
        slice_device_count=16,
        loader_files=4,
    )
    problems, _ = check_rescale(old, odd)
    assert problems == []


def test_check_rescale_legacy_fingerprint_skips_slice_checks():
    """v1 fingerprints (no slice fields) must keep loading: the slice
    checks treat missing fields as wildcard."""
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    v1 = {
        k: v
        for k, v in _fp().items()
        if not k.startswith("slice_") and k != "num_slices"
    }
    problems, changed = check_rescale(v1, _slice_fp(2))
    assert problems == [] and changed is True


def test_legacy_no_slice_fields_gate_loads_with_note(tmp_path):
    """A checkpoint stamped by pre-multi-slice code (v1 fingerprint)
    loads through the gate with an explicit note that the slice
    fault-domain checks were skipped."""
    v1 = {
        k: v
        for k, v in _fp().items()
        if not k.startswith("slice_") and k != "num_slices"
    }
    state = _saved_ckpt(tmp_path, fingerprint=v1)
    # the live world rescaled too (1 host) so the gate actually runs
    new = _fp(process_count=1, device_count=4, loader_files=1)
    ck, msgs = _loader_ckp(tmp_path, new)
    _, _, step, _, resuming = ck.load(state, None)
    assert (step, resuming) == (4, True)
    assert any("predates slice-aware" in m for m in msgs), msgs


def test_check_rescale_missing_loader_files(tmp_path):
    from fms_fsdp_tpu.ckpt.elastic import check_rescale

    (tmp_path / "loader_state_0.pkl").write_bytes(b"x")
    new = _fp(process_count=1, device_count=4, loader_files=1)
    old = _fp(loader_files=4)  # saved by 4 loader ranks, only 1 on disk
    problems, _ = check_rescale(old, new, ckp_dir=str(tmp_path))
    assert any("incomplete" in p for p in problems)


def test_elastic_batch_size_policy(capsys):
    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.data.loader import elastic_batch_size

    cfg = TrainConfig(batch_size=2)
    # fresh start / same global batch: untouched
    assert elastic_batch_size(cfg, None, 8) == 2
    assert elastic_batch_size(cfg, {"global_batch_rows": 16}, 8) == 2
    # halved extent: per-rank rows double to preserve the global batch
    assert elastic_batch_size(cfg, {"global_batch_rows": 16}, 4) == 4
    assert "preserving the global batch" in capsys.readouterr().out
    # non-dividing extent: hard error naming the escape hatch
    with pytest.raises(ValueError, match="allow_batch_change"):
        elastic_batch_size(cfg, {"global_batch_rows": 16}, 3)
    # escape hatch: configured batch respected, loud warning
    cfg.allow_batch_change = True
    assert elastic_batch_size(cfg, {"global_batch_rows": 16}, 3) == 2
    assert "changes the global batch" in capsys.readouterr().out


# ---- checkpoint gate (single process, tiny states) -------------------------


class _TwoRankLoaderStub:
    """Writes the loader_state files a 2-rank save would have."""

    def save_to_path(self, path):
        import pickle

        os.makedirs(path, exist_ok=True)
        for r in range(2):
            with open(os.path.join(path, f"loader_state_{r}.pkl"), "wb") as f:
                pickle.dump({"rank": r}, f)


def _saved_ckpt(tmp_path, fingerprint=_fp(), with_loader=True):
    import jax.numpy as jnp

    from fms_fsdp_tpu.utils.checkpointing import Checkpointer

    ck = Checkpointer(str(tmp_path), 3, "fsdp", rank=0)
    if fingerprint is not None:
        ck.set_fingerprint(fingerprint)
    state = {"w": jnp.arange(4.0), "step": jnp.zeros((), jnp.int32)}
    ck.save(
        4,
        state,
        _TwoRankLoaderStub() if with_loader else None,
        tokens_seen=44,
    )
    return state


def _loader_ckp(tmp_path, fingerprint, allow_batch_change=False):
    from fms_fsdp_tpu.utils.checkpointing import Checkpointer

    msgs = []

    def report(*a, **k):
        msgs.append(" ".join(str(x) for x in a))

    ck = Checkpointer(str(tmp_path), 3, "fsdp", rank=0, report_fn=report)
    if fingerprint is not None:
        ck.set_fingerprint(fingerprint, allow_batch_change=allow_batch_change)
    return ck, msgs


def test_same_topology_resume_is_silent_noop(tmp_path):
    state = _saved_ckpt(tmp_path)
    ck, msgs = _loader_ckp(tmp_path, _fp())
    _, _, step, ntok, resuming = ck.load(state, None)
    assert (step, ntok, resuming) == (4, 44, True)
    assert not any("Elastic resume" in m for m in msgs)


def test_legal_rescale_loads_with_notice(tmp_path):
    state = _saved_ckpt(tmp_path)
    new = _fp(process_count=1, device_count=4, loader_files=1)
    ck, msgs = _loader_ckp(tmp_path, new)
    _, _, step, _, resuming = ck.load(state, None)
    assert (step, resuming) == (4, True)
    assert any("Elastic resume" in m for m in msgs), msgs


def test_illegal_rescale_fails_fast_with_actionable_error(tmp_path):
    state = _saved_ckpt(tmp_path)
    new = _fp(process_count=3, device_count=12, loader_files=3)
    ck, _ = _loader_ckp(tmp_path, new, allow_batch_change=True)
    with pytest.raises(RuntimeError, match="does not divide n_logical_shards"):
        ck.load(state, None)


def test_missing_loader_file_fails_fast(tmp_path):
    state = _saved_ckpt(tmp_path)
    victim = os.path.join(
        str(tmp_path), "checkpoints", "step_4_ckp", "loader_state_1.pkl"
    )
    os.remove(victim)
    new = _fp(process_count=1, device_count=4, loader_files=1)
    ck, _ = _loader_ckp(tmp_path, new)
    with pytest.raises(RuntimeError, match="incomplete"):
        ck.load(state, None)


def test_batch_change_blocked_without_flag(tmp_path):
    state = _saved_ckpt(tmp_path)
    new = _fp(process_count=1, device_count=4, loader_files=1,
              global_batch_rows=4)
    ck, _ = _loader_ckp(tmp_path, new)
    with pytest.raises(RuntimeError, match="allow_batch_change"):
        ck.load(state, None)
    ck2, msgs = _loader_ckp(tmp_path, new, allow_batch_change=True)
    _, _, step, _, _ = ck2.load(state, None)
    assert step == 4


def test_legacy_checkpoint_without_topology_loads(tmp_path):
    state = _saved_ckpt(tmp_path, fingerprint=None)
    ck, msgs = _loader_ckp(tmp_path, _fp())
    _, _, step, _, resuming = ck.load(state, None)
    assert (step, resuming) == (4, True)
    assert any("predates topology fingerprints" in m for m in msgs)


def test_resume_topology_skips_corrupt_newest_checkpoint(tmp_path):
    """The batch-policy scan walks the same manifest-verified fallback
    chain as load(): a corrupt newest checkpoint with an intact
    metadata.json must not set a policy the restore then contradicts by
    falling back to an older (differently-batched) checkpoint."""
    import jax.numpy as jnp

    from fms_fsdp_tpu.utils.checkpointing import Checkpointer

    ck = Checkpointer(str(tmp_path), 3, "fsdp", rank=0)
    state = {"w": jnp.arange(4.0), "step": jnp.zeros((), jnp.int32)}
    ck.set_fingerprint(_fp(global_batch_rows=16))
    ck.save(4, state, _TwoRankLoaderStub(), tokens_seen=44)
    ck.set_fingerprint(_fp(global_batch_rows=32))
    ck.save(8, state, _TwoRankLoaderStub(), tokens_seen=88)
    assert ck.resume_topology()["global_batch_rows"] == 32
    # truncate a manifest-covered payload file in the newest checkpoint,
    # leaving its metadata.json intact (the ckpt_corrupt failure class;
    # loader_state files are deliberately outside the manifest's scope)
    import json

    step8 = os.path.join(str(tmp_path), "checkpoints", "step_8_ckp")
    with open(os.path.join(step8, "manifest.json")) as f:
        covered = [
            rel
            for rel, size in json.load(f)["files"].items()
            if size > 0
        ]
    victim = os.path.join(step8, sorted(covered)[0])
    with open(victim, "rb+") as f:
        f.truncate(os.path.getsize(victim) // 2)
    # the scan now resolves the checkpoint load() will actually restore
    assert ck.resume_topology()["global_batch_rows"] == 16


def test_manager_stamps_topology_on_every_tier(tmp_path):
    """Both async tiers stamp the fingerprint; resume_topology reads the
    newest committed one back (the entry's elastic preflight)."""
    import json

    import jax.numpy as jnp

    from fms_fsdp_tpu.ckpt.manager import (
        AsyncCheckpointManager,
        CheckpointTier,
    )

    tiers = [
        CheckpointTier("local", str(tmp_path / "local"), 2, 2, "fsdp", rank=0),
        CheckpointTier("durable", str(tmp_path / "dur"), 4, 3, "fsdp", rank=0),
    ]
    m = AsyncCheckpointManager(tiers, async_save=False, rank=0)
    m.set_fingerprint(_fp())
    state = {"w": jnp.arange(4.0)}
    m.save(2, state, None, tokens_seen=2)  # local tier
    m.save(4, state, None, tokens_seen=4)  # durable tier
    m.finalize()
    for root, step in ((tmp_path / "local", 2), (tmp_path / "dur", 4)):
        meta = json.loads(
            (root / "checkpoints" / f"step_{step}_ckp" / "metadata.json")
            .read_text()
        )
        # no dataloader rode along: loader_files stamped 0
        assert meta["topology"] == _fp(loader_files=0), meta
    assert m.resume_topology() == _fp(loader_files=0)


def test_streaming_rescale_error_is_actionable(tmp_path):
    """The bare reader's no-rescale assert is a real diagnostic now."""
    from fms_fsdp_tpu.data.handlers import ArrowHandler
    from fms_fsdp_tpu.data.streaming import StreamingDocDataset

    datadir = _id_corpus(tmp_path / "data")
    ckdir = str(tmp_path / "bare_ckpt")
    for rank in range(2):  # a 2-rank save of the bare reader
        d = StreamingDocDataset(
            os.path.join(datadir, "dataset_1"), rank, 2, ArrowHandler(), -1,
            max_chunksize=1000,
        )
        d.save_to_path(ckdir)
    d2 = StreamingDocDataset(
        os.path.join(datadir, "dataset_1"), 0, 1, ArrowHandler(), -1,
        max_chunksize=1000,
    )
    with pytest.raises(RuntimeError, match="ScalableShardDataset"):
        d2.load_from_path(ckdir)


# ---- document walk across a rescale (data layer) ---------------------------


def _id_corpus(root, n_docs=100, doc_len=100):
    """One shard of ``n_docs`` docs; doc i = [i*100 .. i*100+99], so the
    first token identifies the document."""
    root = str(root)
    os.makedirs(os.path.join(root, "dataset_1"), exist_ok=True)
    schema = pa.schema([pa.field("tokens", pa.uint32())])
    with pa.ipc.new_file(
        os.path.join(root, "dataset_1", "shard.arrow"), schema
    ) as w:
        for i in range(n_docs):
            w.write(
                pa.record_batch(
                    [list(range(i * 100, i * 100 + doc_len))], schema
                )
            )
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        f.write(f"/dataset_1/shard.arrow,{n_docs},{n_docs * doc_len}\n")
    return root


def _scalable(rank, worldsize, datadir):
    from fms_fsdp_tpu.data.handlers import ArrowHandler
    from fms_fsdp_tpu.data.streaming import (
        ScalableShardDataset,
        StreamingDocDataset,
    )

    return ScalableShardDataset(
        StreamingDocDataset(
            os.path.join(datadir, "dataset_1"), rank, worldsize,
            ArrowHandler(), -1, max_chunksize=1000,
        ),
        -1,
        n_logical_shards=8,
    )


@pytest.mark.parametrize("new_world", [1, 4])
def test_document_walk_continues_across_rescale(tmp_path, new_world):
    """Mid-epoch save at world 2 -> per-rank loader_state files ->
    restore at world 1 / 4 -> finish the epoch: every document of the
    epoch appears exactly once across the boundary. Exact coverage is
    the no-replay AND no-skip proof in one (pigeonhole: 60 + 40 distinct
    docs over a 100-doc epoch)."""
    datadir = _id_corpus(tmp_path / "data")
    ds = [_scalable(i, 2, datadir) for i in range(2)]
    its = [iter(d) for d in ds]
    seen_before = [int(next(its[0])[0]) for _ in range(25)]
    seen_before += [int(next(its[1])[0]) for _ in range(35)]
    ckdir = str(tmp_path / "loader_ckpt")
    for d in ds:
        d.save_to_path(ckdir)

    ds2 = [_scalable(i, new_world, datadir) for i in range(new_world)]
    seen_after = []
    for d in ds2:
        d.load_from_path(ckdir)
        remaining = sum(d.n_docs_remaining)
        it = iter(d)
        seen_after += [int(next(it)[0]) for _ in range(remaining)]

    walk = sorted(seen_before + seen_after)
    assert walk == [i * 100 for i in range(100)], (
        f"document walk shifted across the rescale: "
        f"{len(seen_before)} + {len(seen_after)} docs, "
        f"{len(set(walk))} distinct"
    )


# ---- e2e: gloo multi-process world, production stack -----------------------


def _marked_corpus(root, n_shards=4, docs_per_shard=200, doc_len=40):
    """Arrow corpus where doc d opens with the unique marker token
    MARKER_BASE+d (body tokens stay below MARKER_BASE): any marker
    appearing twice in the trainer-consumed stream is a replayed
    document."""
    root = str(root)
    os.makedirs(os.path.join(root, "dataset_1"), exist_ok=True)
    schema = pa.schema([pa.field("tokens", pa.uint32())])
    rows = []
    d = 0
    for s in range(n_shards):
        path = os.path.join(root, "dataset_1", f"shard_{s}.arrow")
        with pa.ipc.new_file(path, schema) as w:
            for _ in range(docs_per_shard):
                body = [(d * 31 + j) % 997 + 1 for j in range(doc_len - 1)]
                w.write(
                    pa.record_batch([[MARKER_BASE + d] + body], schema)
                )
                d += 1
        rows.append((f"/dataset_1/shard_{s}.arrow", docs_per_shard,
                     docs_per_shard * doc_len))
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        for name, docs, toks in rows:
            f.write(f"{name},{docs},{toks}\n")
    return root


def _marked_mixed_corpus(root, corpora=3, docs_per_corpus=300, doc_len=80):
    """Three-corpus variant of ``_marked_corpus``: corpus c's documents
    carry markers in the disjoint range [MARKER_BASE + c*docs_per_corpus,
    MARKER_BASE + (c+1)*docs_per_corpus), so replay checks work
    per-corpus. All markers stay below the child's vocab_size=2048."""
    root = str(root)
    assert MARKER_BASE + corpora * docs_per_corpus <= 2048
    schema = pa.schema([pa.field("tokens", pa.uint32())])
    rows = []
    for c in range(corpora):
        name = f"dataset_{c + 1}"
        os.makedirs(os.path.join(root, name), exist_ok=True)
        base = MARKER_BASE + c * docs_per_corpus
        d = 0
        for s in range(2):
            path = os.path.join(root, name, f"shard_{s}.arrow")
            with pa.ipc.new_file(path, schema) as w:
                for _ in range(docs_per_corpus // 2):
                    body = [
                        ((base + d) * 31 + j) % 997 + 1
                        for j in range(doc_len - 1)
                    ]
                    w.write(pa.record_batch([[base + d] + body], schema))
                    d += 1
            rows.append(
                (f"/{name}/shard_{s}.arrow", docs_per_corpus // 2,
                 (docs_per_corpus // 2) * doc_len)
            )
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        for name, docs, toks in rows:
            f.write(f"{name},{docs},{toks}\n")
    return root


def _corpus_of(marker, docs_per_corpus=300):
    return (marker - MARKER_BASE) // docs_per_corpus


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_world(n_procs, argv, timeout=600):
    """Run the elastic child on an n-process gloo world; returns
    (returncodes, outputs)."""
    port = _free_port()
    procs = []
    for pid in range(n_procs):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
        )
        if n_procs > 1:
            env.update(
                COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                NUM_PROCESSES=str(n_procs),
                PROCESS_ID=str(pid),
            )
        else:
            # a true single-process restart: no distributed world at all
            for k in ("COORDINATOR_ADDRESS", "NUM_PROCESSES", "PROCESS_ID"):
                env.pop(k, None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-u", CHILD, *argv],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
                cwd=REPO,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    return [p.returncode for p in procs], outs


def _grab(out, key):
    for line in out.splitlines():
        if line.startswith(key + " "):
            return line.split(" ", 1)[1].strip()
    raise AssertionError(f"{key} not found in:\n{out[-3000:]}")


def _walk_markers(walk_dir, phase):
    markers = []
    for name in sorted(os.listdir(walk_dir)):
        if name.startswith(f"walk_{phase}_"):
            with open(os.path.join(walk_dir, name)) as f:
                # "B" lines are per-batch separators (the chaos-soak
                # driver's committed-prefix reconstruction); skip them
                markers += [int(x) for x in f.read().split() if x != "B"]
    return markers


@pytest.mark.slow
def test_elastic_resume_world2_to_world1(tmp_path):
    """Train at world=2 on real arrow data, commit at step 4; a
    same-topology resume is a fingerprint no-op; a world=1 resume
    restores bit-identically onto the new mesh, preserves the global
    batch (per-rank rows 2 -> 4), and continues the trainer-consumed
    document stream with zero replayed documents.

    The run trains with quantized_reduce="fp8_delayed", so the
    delayed-scaling amax history rides in the train state: STATE_HASH
    equality across worlds pins that the quant subtree elastic-reshards
    bit-identically, and QUANT_AMAX_NONZERO pins that the restored
    history is the live one (a silent re-init would print 0)."""
    data = _marked_corpus(tmp_path / "data")
    ckpt = str(tmp_path / "ckpt")
    walk = str(tmp_path / "walk")
    os.makedirs(walk)
    quant = ["", "quantized_reduce=fp8_delayed"]

    rcs, outs = _launch_world(
        2, [ckpt, data, walk, "save", "4", "4", *quant]
    )
    assert rcs == [0, 0], outs[0][-3000:] + outs[1][-3000:]

    # same-topology restart: the fingerprint check is a no-op
    rcs, outs_same = _launch_world(
        2, [ckpt, data, walk, "same", "4", "4", *quant]
    )
    assert rcs == [0, 0], outs_same[0][-3000:] + outs_same[1][-3000:]
    assert _grab(outs_same[0], "START_STEP") == "4"
    assert "Elastic resume" not in outs_same[0], outs_same[0][-3000:]
    ref_hash = _grab(outs_same[0], "STATE_HASH")
    assert _grab(outs_same[1], "STATE_HASH") == ref_hash
    assert int(_grab(outs_same[0], "QUANT_AMAX_NONZERO")) > 0

    # world=1 rescale: bit-identical restore, preserved global batch,
    # seamless walk continuation
    rcs, outs_r = _launch_world(
        1, [ckpt, data, walk, "resume", "8", "4", *quant]
    )
    assert rcs == [0], outs_r[0][-4000:]
    out = outs_r[0]
    assert _grab(out, "START_STEP") == "4"
    assert _grab(out, "STATE_HASH") == ref_hash, out[-3000:]
    # the amax history survived the rescale as live data
    assert int(_grab(out, "QUANT_AMAX_NONZERO")) > 0
    assert "preserving the global batch of 16 rows" in out, out[-3000:]
    assert "Elastic resume: restart topology differs" in out, out[-3000:]
    losses = [
        float(ln.split("loss:")[1].strip().split()[0])
        for ln in out.splitlines()
        if ln.startswith("loss:")
    ]
    assert losses and all(np.isfinite(losses)), out[-2000:]

    before = _walk_markers(walk, "save")
    after = _walk_markers(walk, "resume")
    assert before and after, (len(before), len(after))
    both = before + after
    assert len(both) == len(set(both)), (
        f"replayed documents across the rescale: "
        f"{sorted(m for m in set(both) if both.count(m) > 1)[:10]}"
    )


@pytest.mark.slow
def test_elastic_resume_world4_after_midsave_kill(tmp_path):
    """The save world dies BETWEEN snapshot and commit at step 8 (the
    PR 3 ckpt_precommit_kill site): step_8 is torn, step_4 committed. A
    world=1 and a world=4 restart must both fall back to step 4 and
    restore the identical state onto their different meshes."""
    data = _marked_corpus(tmp_path / "data")
    ckpt = str(tmp_path / "ckpt")
    walk = str(tmp_path / "walk")
    os.makedirs(walk)

    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-u", CHILD, ckpt, data, walk, "save",
                    "12", "4", "ckpt_precommit_kill:step=8",
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
                cwd=REPO,
            )
        )
    try:
        out0, _ = procs[0].communicate(timeout=600)
        assert procs[0].returncode != 0, (
            "rank 0 should die mid-commit\n" + out0[-3000:]
        )
    finally:
        # rank 1 loses its peer mid-collective; reap it
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            if p.poll() is None:
                try:
                    p.communicate(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()

    ckdir = os.path.join(ckpt, "checkpoints")
    entries = os.listdir(ckdir)
    assert "step_4_ckp" in entries and "step_8_ckp" in entries, entries
    assert "metadata.json" in os.listdir(os.path.join(ckdir, "step_4_ckp"))
    assert "metadata.json" not in os.listdir(
        os.path.join(ckdir, "step_8_ckp")
    ), "step 8 should be uncommitted"

    rcs, outs1 = _launch_world(1, [ckpt, data, walk, "cross", "4", "4"])
    assert rcs == [0], outs1[0][-4000:]
    assert _grab(outs1[0], "START_STEP") == "4"
    h1 = _grab(outs1[0], "STATE_HASH")

    rcs, outs4 = _launch_world(4, [ckpt, data, walk, "resume4", "8", "4"])
    assert rcs == [0, 0, 0, 0], "\n".join(o[-2000:] for o in outs4)
    assert _grab(outs4[0], "START_STEP") == "4"
    for o in outs4:
        assert _grab(o, "STATE_HASH") == h1, o[-3000:]
    assert "ELASTIC_CHILD_DONE" in outs4[0]


@pytest.mark.slow
def test_multislice_slice_loss_resume(tmp_path):
    """The multi-slice fault-domain e2e (docs/resilience.md "Slice fault
    domains"): a 2-slice x 2-host gloo world (4 processes, 4 virtual
    devices each — mesh dcn=2, fsdp=8) trains over real arrow data to a
    committed checkpoint, then loses slice 1 whole (the ``slice_kill``
    fault site) mid-run:

    - every SURVIVING host fail-fasts with the classified report —
      "slice 1 lost ... world minus one fault domain" — instead of
      hanging in the dead slice's DCN collective (the parent's
      communicate() timeout IS the no-hang assertion);
    - the restart on the surviving slice's shape (1 slice x 2 hosts)
      restores bit-identically (topology-independent STATE_HASH equal to
      the 2-slice world's), preserves the 32-row global batch (per-rank
      rows 2 -> 4), and continues the committed document walk with zero
      replayed markers;
    - the 2-slice phases' metrics.jsonl carries the schema-v5 collective
      split with real cross-slice (dcn) probe time.
    """
    import json

    # longer docs than the default corpus: the walk runs ahead of
    # consumption by the shuffle window + prefetch on EVERY phase, and
    # this test spans three training phases over a 4-way world — 80-token
    # docs keep every per-rank partition inside epoch 1 for the whole
    # test, so any duplicate marker is a genuine replay, never a
    # legitimate epoch-2 re-serve
    data = _marked_corpus(tmp_path / "data", doc_len=80)
    ckpt = str(tmp_path / "ckpt")
    walk = str(tmp_path / "walk")
    os.makedirs(walk)
    obs_save = str(tmp_path / "obs_save")

    def slice_over(phase):
        return [
            "num_slices=2",
            f"slice_heartbeat_dir={tmp_path / ('hb_' + phase)}",
            "slice_timeout_s=8",
        ]

    # ---- phase 1: clean 2-slice train, commit at step 4 ----
    rcs, outs = _launch_world(
        4,
        [ckpt, data, walk, "save", "4", "4", "",
         *slice_over("save"), f"obs_dir={obs_save}"],
    )
    assert rcs == [0, 0, 0, 0], "\n".join(o[-2000:] for o in outs)
    assert _grab(outs[0], "SLICE_CTX") == "2 0", outs[0][-2000:]
    assert _grab(outs[3], "SLICE_CTX") == "2 1", outs[3][-2000:]
    with open(os.path.join(obs_save, "metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    from fms_fsdp_tpu.obs.schema import SCHEMA_VERSION

    assert recs and all(
        r["schema_version"] == SCHEMA_VERSION for r in recs
    ), recs
    assert any(r["dcn_collective_s"] > 0 for r in recs), recs
    assert any(r["ici_collective_s"] > 0 for r in recs), recs

    # ---- phase 2: same-topology restart = fingerprint no-op; the
    # reference hash for the cross-topology comparison ----
    rcs, outs_same = _launch_world(
        4, [ckpt, data, walk, "same", "4", "4", "", *slice_over("same")]
    )
    assert rcs == [0, 0, 0, 0], "\n".join(o[-2000:] for o in outs_same)
    assert _grab(outs_same[0], "START_STEP") == "4"
    assert "Elastic resume" not in outs_same[0], outs_same[0][-3000:]
    ref_hash = _grab(outs_same[0], "STATE_HASH")
    for o in outs_same[1:]:
        assert _grab(o, "STATE_HASH") == ref_hash

    # ---- phase 3: slice 1 dies whole at step 6 (no commit since 4).
    # Survivors must exit (not hang) with the fault domain named. ----
    rcs, outs_kill = _launch_world(
        4,
        [ckpt, data, walk, "killed", "12", "8",
         "slice_kill:slice=1:step=6", *slice_over("killed")],
    )
    assert all(rc != 0 for rc in rcs), rcs
    survivor_out = outs_kill[0] + outs_kill[1]
    assert "slice 1 lost" in survivor_out, survivor_out[-4000:]
    assert "world minus one fault domain" in survivor_out, survivor_out[-4000:]
    ckdir = os.path.join(ckpt, "checkpoints")
    committed = [
        d
        for d in os.listdir(ckdir)
        if d.startswith("step_")
        and "metadata.json" in os.listdir(os.path.join(ckdir, d))
    ]
    assert committed == ["step_4_ckp"], committed

    # ---- phase 4: restart at world minus one fault domain (the
    # surviving slice's shape: 1 slice x 2 hosts) ----
    rcs, outs_r = _launch_world(2, [ckpt, data, walk, "resume", "8", "4"])
    assert rcs == [0, 0], outs_r[0][-4000:] + outs_r[1][-4000:]
    out = outs_r[0]
    assert _grab(out, "SLICE_CTX") == "1 0"
    assert _grab(out, "START_STEP") == "4"
    assert _grab(out, "STATE_HASH") == ref_hash, out[-3000:]
    assert "preserving the global batch of 32 rows" in out, out[-3000:]
    assert "Elastic resume: restart topology differs" in out, out[-3000:]
    losses = [
        float(ln.split("loss:")[1].strip().split()[0])
        for ln in out.splitlines()
        if ln.startswith("loss:")
    ]
    assert losses and all(np.isfinite(losses)), out[-2000:]

    # zero replayed markers across the committed-checkpoint boundary
    # (the killed phase's consumed-but-uncommitted rows are excluded:
    # work since the last commit is redone by design — PR 3 semantics)
    before = _walk_markers(walk, "save")
    after = _walk_markers(walk, "resume")
    assert before and after, (len(before), len(after))
    both = before + after
    assert len(both) == len(set(both)), (
        f"replayed documents across the slice-loss resume: "
        f"{sorted(m for m in set(both) if both.count(m) > 1)[:10]}"
    )


@pytest.mark.slow
def test_elastic_mixed_corpus_shrink_resume(tmp_path):
    """The weighted 3-corpus shrink-restart e2e (docs/dataloader.md
    "Multi-corpus mixing"): train at world=2 over three corpora mixed
    2:1:1, commit at step 4, then

    - a same-topology restart is a fingerprint no-op whose restored mix
      state carries nonzero per-corpus tokens_seen (MIX_TOKENS — pairing
      is by corpus name);
    - a world=1 shrink-restart restores the train state bit-identically
      (topology-independent STATE_HASH), preserves the 16-row global
      batch, and continues every corpus's document walk with zero
      replayed markers — the v3 fingerprint (corpus_names +
      mix_weights_digest) rides the same gate as every other topology
      field.
    """
    data = _marked_mixed_corpus(tmp_path / "data")
    ckpt = str(tmp_path / "ckpt")
    walk = str(tmp_path / "walk")
    os.makedirs(walk)
    mix = ["", "datasets=dataset_1,dataset_2,dataset_3", "weights=2,1,1"]

    rcs, outs = _launch_world(2, [ckpt, data, walk, "save", "4", "4", *mix])
    assert rcs == [0, 0], outs[0][-3000:] + outs[1][-3000:]

    # same-topology restart: fingerprint no-op, name-keyed mix state back
    rcs, outs_same = _launch_world(
        2, [ckpt, data, walk, "same", "4", "4", *mix]
    )
    assert rcs == [0, 0], outs_same[0][-3000:] + outs_same[1][-3000:]
    assert _grab(outs_same[0], "START_STEP") == "4"
    assert "Elastic resume" not in outs_same[0], outs_same[0][-3000:]
    ref_hash = _grab(outs_same[0], "STATE_HASH")
    assert _grab(outs_same[1], "STATE_HASH") == ref_hash
    mix_tokens = dict(
        kv.split("=") for kv in _grab(outs_same[0], "MIX_TOKENS").split()
    )
    assert set(mix_tokens) == {"dataset_1", "dataset_2", "dataset_3"}
    assert sum(int(v) for v in mix_tokens.values()) > 0, mix_tokens
    assert _grab(outs_same[0], "MIX_QUARANTINED") == "-"

    # world=1 shrink: bit-identical restore, preserved global batch,
    # per-corpus walk continuation
    rcs, outs_r = _launch_world(
        1, [ckpt, data, walk, "resume", "8", "4", *mix]
    )
    assert rcs == [0], outs_r[0][-4000:]
    out = outs_r[0]
    assert _grab(out, "START_STEP") == "4"
    assert _grab(out, "STATE_HASH") == ref_hash, out[-3000:]
    assert "preserving the global batch of 16 rows" in out, out[-3000:]
    assert "Elastic resume: restart topology differs" in out, out[-3000:]
    # the rescale resets the per-corpus token targets (scalar mix state
    # drops, like every position scalar) — but the walks reshard exactly
    assert set(
        kv.split("=")[0]
        for kv in _grab(out, "MIX_TOKENS").split()
    ) == {"dataset_1", "dataset_2", "dataset_3"}

    before = _walk_markers(walk, "save")
    after = _walk_markers(walk, "resume")
    assert before and after, (len(before), len(after))
    for c in range(3):
        b = [m for m in before if _corpus_of(m) == c]
        a = [m for m in after if _corpus_of(m) == c]
        assert b and a, (
            f"corpus {c + 1} missing from a phase "
            f"({len(b)} before, {len(a)} after)"
        )
        both = b + a
        assert len(both) == len(set(both)), (
            f"corpus {c + 1} replayed documents across the shrink: "
            f"{sorted(m for m in set(both) if both.count(m) > 1)[:10]}"
        )
    # the 2:1:1 weighting is visible in the document stream
    counts = [len([m for m in before if _corpus_of(m) == c]) for c in range(3)]
    assert counts[0] > counts[1] and counts[0] > counts[2], counts
