"""Context-parallel SSD scan: sequence sharded over the context axis
with the inter-chunk state passed explicitly across devices must equal
the single-device chunked scan exactly — forward and gradients (the
recurrence is linear in the carried state, so the per-device zero-init
scan plus decayed initial-state correction is algebraically identical).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fms_fsdp_tpu.ops.ssd import ssd_scan, ssd_scan_cp
from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh


def _inputs(b=2, s=256, h=4, p=8, g=2, n=8, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    x = jax.random.normal(ks[0], (b, s, h, p), dtype)
    dt = jax.nn.softplus(jax.random.normal(ks[1], (b, s, h), jnp.float32))
    A = -jnp.exp(jax.random.normal(ks[2], (h,), jnp.float32))
    Bm = jax.random.normal(ks[3], (b, s, g, n), dtype)
    Cm = jax.random.normal(ks[4], (b, s, g, n), dtype)
    D = jnp.ones((h,), jnp.float32) * 0.5
    return x, dt, A, Bm, Cm, D


@pytest.mark.parametrize("cp", [2, 4])
def test_ssd_cp_matches_full(cp):
    x, dt, A, Bm, Cm, D = _inputs()
    mesh = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=cp)
    )
    ref = ssd_scan(x, dt, A, Bm, Cm, D, chunk_size=32)
    out = jax.jit(
        lambda *a: ssd_scan_cp(*a, mesh=mesh, chunk_size=32)
    )(x, dt, A, Bm, Cm, D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ssd_cp_grads_match_full():
    x, dt, A, Bm, Cm, D = _inputs(seed=3)
    mesh = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )

    # A and D ride into shard_map replicated (P(None) specs) — their
    # cotangents flow through the psum-on-transpose path, which none of
    # the sharded-operand grads exercise (ADVICE r4): cover all six
    def loss_full(x, dt, Bm, Cm, A, D):
        return jnp.sum(ssd_scan(x, dt, A, Bm, Cm, D, chunk_size=32) ** 2)

    def loss_cp(x, dt, Bm, Cm, A, D):
        return jnp.sum(
            ssd_scan_cp(x, dt, A, Bm, Cm, D, mesh=mesh, chunk_size=32) ** 2
        )

    argnums = (0, 1, 2, 3, 4, 5)
    ref = jax.grad(loss_full, argnums=argnums)(x, dt, Bm, Cm, A, D)
    out = jax.jit(jax.grad(loss_cp, argnums=argnums))(x, dt, Bm, Cm, A, D)
    for a, b in zip(out, ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-4
        )


def test_mamba_forward_context_parallel():
    """Whole hybrid model (mamba mixers + one interleaved attention
    layer) under a context axis: the cp path (ssd_scan_cp + ring
    attention) must reproduce the single-device forward."""
    from fms_fsdp_tpu.models.configs import MambaAttnConfig, MambaConfig
    from fms_fsdp_tpu.models.mamba import init_mamba_params, mamba_forward

    cfg = MambaConfig(
        d_model=64,
        d_intermediate=96,
        n_layer=3,
        vocab_size=256,
        attn_layer_idx=(1,),
        attn_cfg=MambaAttnConfig(
            head_dim=16, num_heads=4, num_heads_kv=2, rotary_emb_dim=8
        ),
        d_state=16,
        headdim=16,
        chunk_size=16,
    )
    params = init_mamba_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256)

    ref = mamba_forward(
        params, tokens, cfg, compute_dtype=jnp.float32, attn_impl="xla"
    )
    mesh = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )
    out = jax.jit(
        lambda p, t: mamba_forward(
            p, t, cfg, compute_dtype=jnp.float32, attn_impl="xla", mesh=mesh
        )
    )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=5e-4, rtol=1e-4
    )


def test_ssd_cp_bf16():
    """Production dtype: bf16 operands, fp32 state — cp must track the
    single-device scan at bf16 tolerance."""
    x, dt, A, Bm, Cm, D = _inputs(seed=7, dtype=jnp.bfloat16)
    mesh = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )
    ref = ssd_scan(x, dt, A, Bm, Cm, D, chunk_size=32)
    out = jax.jit(
        lambda *a: ssd_scan_cp(*a, mesh=mesh, chunk_size=32)
    )(x, dt, A, Bm, Cm, D)
    # bf16 casts sit at different points in the two paths (the cp D-term
    # adds after the shard_map output cast), so isolated elements differ
    # by one bf16 ulp-chain — bound abs error loosely, mean tightly
    a = np.asarray(out, np.float32)
    b = np.asarray(ref, np.float32)
    np.testing.assert_allclose(a, b, atol=1e-1, rtol=1e-1)
    assert np.mean(np.abs(a - b)) < 5e-3
