"""int8 quantized matmul tests: forward accuracy, straight-through
backward, dispatch, and a train-step smoke with quantization enabled."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fms_fsdp_tpu.ops.quant import int8_matmul, int8_matmul_dgrad, matmul


def _xw(seed=0, t=64, d=256, f=128):
    kx, kw = jax.random.split(jax.random.PRNGKey(seed))
    x = jax.random.normal(kx, (2, t, d), jnp.float32)
    w = jax.random.normal(kw, (d, f), jnp.float32) * 0.02
    return x, w


def test_int8_forward_close():
    x, w = _xw()
    ref = x @ w
    out = int8_matmul(x, w)
    rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
    assert rel < 0.02, rel


def test_int8_backward_is_bf16_grads():
    """The VJP must be exactly the unquantized matmul's gradients
    evaluated at the same (x, w) and upstream cotangent."""
    x, w = _xw()
    g = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 128), jnp.float32)

    def via(mm):
        _, vjp = jax.vjp(mm, x, w)
        return vjp(g)

    dx_q, dw_q = via(int8_matmul)
    dx_r, dw_r = via(lambda x, w: x @ w)
    np.testing.assert_allclose(np.asarray(dx_q), np.asarray(dx_r), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dw_q), np.asarray(dw_r), rtol=1e-5)


def test_int8_dgrad_close_to_exact():
    x, w = _xw()
    g = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 128), jnp.float32)
    _, vjp = jax.vjp(int8_matmul_dgrad, x, w)
    dx_q, dw_q = vjp(g)
    _, vjp_r = jax.vjp(lambda x, w: x @ w, x, w)
    dx_r, dw_r = vjp_r(g)
    rel = float(jnp.linalg.norm(dx_q - dx_r) / jnp.linalg.norm(dx_r))
    assert rel < 0.02, rel
    # wgrad stays exact bf16 math
    np.testing.assert_allclose(np.asarray(dw_q), np.asarray(dw_r), rtol=1e-5)


def test_zero_input_safe():
    x = jnp.zeros((1, 8, 256))
    w = jnp.zeros((256, 128))
    out = int8_matmul(x, w)
    assert not bool(jnp.any(jnp.isnan(out)))


@pytest.mark.parametrize("quant", ["none", "int8", "int8_dgrad"])
def test_dispatch(quant):
    x, w = _xw()
    out = matmul(x, w, quant=quant)
    assert out.shape == (2, 64, 128)


def _expert_xw(seed=0, b=2, e=4, c=16, d=64, f=48):
    kx, kw = jax.random.split(jax.random.PRNGKey(seed))
    x = jax.random.normal(kx, (e, b, c, d), jnp.float32)
    w = jax.random.normal(kw, (e, d, f), jnp.float32) * 0.02
    return x, w


def test_int8_expert_forward_close():
    from fms_fsdp_tpu.ops.quant import expert_matmul

    x, w = _expert_xw()
    ref = jnp.einsum("ebcd,edf->ebcf", x, w)
    out = expert_matmul(x, w, quant="int8")
    rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
    assert rel < 0.02, rel


def test_int8_expert_backward_is_bf16_grads():
    from fms_fsdp_tpu.ops.quant import int8_expert_matmul

    x, w = _expert_xw()
    g = jax.random.normal(jax.random.PRNGKey(2), (4, 2, 16, 48), jnp.float32)

    def via(mm):
        _, vjp = jax.vjp(mm, x, w)
        return vjp(g)

    dx_q, dw_q = via(int8_expert_matmul)
    dx_r, dw_r = via(lambda x, w: jnp.einsum("ebcd,edf->ebcf", x, w))
    np.testing.assert_allclose(
        np.asarray(dx_q), np.asarray(dx_r), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(dw_q), np.asarray(dw_r), rtol=1e-5, atol=1e-5
    )


def test_int8_expert_dgrad_close_to_exact():
    from fms_fsdp_tpu.ops.quant import int8_expert_matmul_dgrad

    x, w = _expert_xw()
    g = jax.random.normal(jax.random.PRNGKey(2), (4, 2, 16, 48), jnp.float32)
    _, vjp = jax.vjp(int8_expert_matmul_dgrad, x, w)
    dx_q, dw_q = vjp(g)
    _, vjp_r = jax.vjp(lambda x, w: jnp.einsum("ebcd,edf->ebcf", x, w), x, w)
    dx_r, dw_r = vjp_r(g)
    rel = float(jnp.linalg.norm(dx_q - dx_r) / jnp.linalg.norm(dx_r))
    assert rel < 0.02, rel
    np.testing.assert_allclose(
        np.asarray(dw_q), np.asarray(dw_r), rtol=1e-5, atol=1e-5
    )


def test_mixtral_train_step_with_int8():
    """One Mixtral train step with int8 expert GEMMs: finite loss."""
    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.models.configs import MixtralConfig
    from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh
    from fms_fsdp_tpu.train.step import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    cfg = TrainConfig(
        sharding_strategy="fsdp",
        expert_parallel_size=2,
        batch_size=1,
        seq_length=32,
        num_steps=10,
        quantized_matmuls="int8_dgrad",
        attention_kernel="xla",
    )
    model_cfg = MixtralConfig(
        src_vocab_size=128,
        emb_dim=64,
        nheads=4,
        kvheads=2,
        nlayers=2,
        hidden_dim=96,
        num_experts=4,
        top_k=2,
        max_expected_seq_len=64,
    )
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    state, _ = init_train_state(jax.random.PRNGKey(0), model_cfg, cfg, mesh, opt)
    step_fn = make_train_step(model_cfg, cfg, mesh, opt)
    from fms_fsdp_tpu.parallel.mesh import data_parallel_extent

    n_dp = data_parallel_extent(mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (n_dp, 33), 0, 128, dtype=jnp.int32
    )
    state, metrics = step_fn(state, (tokens[:, :-1], tokens[:, 1:]))
    assert bool(jnp.isfinite(metrics["loss"]))


def test_mamba_train_step_with_int8():
    """One hybrid-Mamba train step with quantized matmuls: finite loss."""
    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.models.configs import MambaAttnConfig, MambaConfig
    from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh
    from fms_fsdp_tpu.train.step import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    cfg = TrainConfig(
        sharding_strategy="fsdp",
        batch_size=1,
        seq_length=32,
        num_steps=10,
        quantized_matmuls="int8_dgrad",
        attention_kernel="xla",
    )
    model_cfg = MambaConfig(
        d_model=64,
        d_intermediate=128,
        n_layer=2,
        vocab_size=128,
        attn_layer_idx=(1,),
        attn_cfg=MambaAttnConfig(
            head_dim=16, num_heads=4, num_heads_kv=2, rotary_emb_dim=8
        ),
        d_state=16,
        headdim=16,
        chunk_size=16,
        pad_vocab_size_multiple=16,
    )
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    state, _ = init_train_state(jax.random.PRNGKey(0), model_cfg, cfg, mesh, opt)
    step_fn = make_train_step(model_cfg, cfg, mesh, opt)
    n_dp = mesh.shape["replica"] * mesh.shape["fsdp"]
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (n_dp, 33), 0, 128, dtype=jnp.int32
    )
    state, metrics = step_fn(state, (tokens[:, :-1], tokens[:, 1:]))
    assert bool(jnp.isfinite(metrics["loss"]))


def test_train_step_with_int8():
    """One llama train step with quantized_matmuls on: finite loss/grads."""
    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.models.configs import LlamaConfig
    from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh
    from fms_fsdp_tpu.train.step import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    cfg = TrainConfig(
        sharding_strategy="fsdp",
        batch_size=1,
        seq_length=64,
        num_steps=10,
        quantized_matmuls="int8_dgrad",
        attention_kernel="xla",
    )
    model_cfg = LlamaConfig(
        src_vocab_size=128,
        emb_dim=64,
        nheads=4,
        kvheads=2,
        nlayers=2,
        multiple_of=16,
        max_expected_seq_len=64,
    )
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    state, _ = init_train_state(jax.random.PRNGKey(0), model_cfg, cfg, mesh, opt)
    step_fn = make_train_step(model_cfg, cfg, mesh, opt)
    n_dp = mesh.shape["replica"] * mesh.shape["fsdp"]
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (n_dp, 65), 0, 128, dtype=jnp.int32
    )
    state, metrics = step_fn(state, (tokens[:, :-1], tokens[:, 1:]))
    assert bool(jnp.isfinite(metrics["loss"]))
    assert bool(jnp.isfinite(metrics["gnorm"]))
