"""End-to-end smoke test of the training entry point on the CPU mesh:
dummy data, hsdp mesh, checkpoint save + resume (the reference's minimum
slice, SURVEY.md §7 step 4)."""

import os

import pytest

import main_training_llama


def _losses(out):
    return [
        float(l.split(":")[1]) for l in out.splitlines() if l.startswith("loss:")
    ]


TINY_OVERRIDES = {
    "LlamaConfig.nlayers": 2,
    "LlamaConfig.emb_dim": 64,
    "LlamaConfig.nheads": 4,
    "LlamaConfig.kvheads": 2,
    "LlamaConfig.src_vocab_size": 256,
    "LlamaConfig.multiple_of": 16,
}


def test_main_training_context_parallel(tmp_path, capsys):
    """Training end-to-end with the sequence sharded over the context
    axis: exercises ring attention's forward AND its ring-level custom-VJP
    backward inside the real jitted train step."""
    main_training_llama.main(
        model_variant="llama2_7b",
        use_dummy_dataset=True,
        num_steps=8,
        seq_length=32,
        batch_size=2,
        report_interval=4,
        checkpoint_interval=1000,
        vocab_size=256,
        sharding_strategy="fsdp",
        context_parallel_size=2,
        attention_kernel="xla",
        ckpt_save_path=str(tmp_path),
        ckpt_load_path=str(tmp_path),
        **TINY_OVERRIDES,
    )
    out = capsys.readouterr().out
    losses = _losses(out)
    assert losses and losses[-1] < losses[0]


def test_main_training_dummy_and_resume(tmp_path, capsys):
    common = dict(
        model_variant="llama2_7b",
        use_dummy_dataset=True,
        seq_length=32,
        batch_size=2,
        report_interval=5,
        checkpoint_interval=10,
        vocab_size=256,
        sharding_strategy="hsdp",
        sharding_group_size=4,
        attention_kernel="xla",
        ckpt_save_path=str(tmp_path),
        ckpt_load_path=str(tmp_path),
        **TINY_OVERRIDES,
    )
    main_training_llama.main(num_steps=12, **common)
    out = capsys.readouterr().out
    assert "step: 10" in out
    assert os.path.isdir(tmp_path / "checkpoints" / "step_10_ckp")
    assert os.path.isdir(tmp_path / "checkpoints" / "step_12_ckp")
    losses = _losses(out)
    assert losses and losses[-1] < losses[0]

    # resume continues from step 12
    main_training_llama.main(num_steps=15, **common)
    out = capsys.readouterr().out
    assert "start_step = 12" in out
    assert "step: 15" in out
    assert os.path.isdir(tmp_path / "checkpoints" / "step_15_ckp")
