"""End-to-end smoke test of the training entry point on the CPU mesh:
dummy data, hsdp mesh, checkpoint save + resume (the reference's minimum
slice, SURVEY.md §7 step 4)."""

import os

import pytest

import main_training_llama


def _losses(out):
    return [
        float(l.split(":")[1]) for l in out.splitlines() if l.startswith("loss:")
    ]


TINY_OVERRIDES = {
    "LlamaConfig.nlayers": 2,
    "LlamaConfig.emb_dim": 64,
    "LlamaConfig.nheads": 4,
    "LlamaConfig.kvheads": 2,
    "LlamaConfig.src_vocab_size": 256,
    "LlamaConfig.multiple_of": 16,
}


def test_main_training_context_parallel(tmp_path, capsys):
    """Training end-to-end with the sequence sharded over the context
    axis: exercises ring attention's forward AND its ring-level custom-VJP
    backward inside the real jitted train step."""
    main_training_llama.main(
        model_variant="llama2_7b",
        use_dummy_dataset=True,
        num_steps=8,
        seq_length=32,
        batch_size=2,
        report_interval=4,
        checkpoint_interval=1000,
        vocab_size=256,
        sharding_strategy="fsdp",
        context_parallel_size=2,
        attention_kernel="xla",
        ckpt_save_path=str(tmp_path),
        ckpt_load_path=str(tmp_path),
        **TINY_OVERRIDES,
    )
    out = capsys.readouterr().out
    losses = _losses(out)
    assert losses and losses[-1] < losses[0]


def test_main_training_mamba_entry(tmp_path, capsys):
    """The mamba ENTRY (shared-orchestration dispatch on MambaConfig):
    tiny hybrid (1 mamba + 1 attention layer) trains and checkpoints —
    the model/step factories have their own tests, this pins the entry
    wiring (variant default, config dispatch, mamba_kernel knob)."""
    import main_training_mamba

    main_training_mamba.main(
        use_dummy_dataset=True,
        num_steps=6,
        seq_length=64,
        batch_size=2,
        report_interval=3,
        checkpoint_interval=6,
        vocab_size=256,
        sharding_strategy="fsdp",
        ckpt_save_path=str(tmp_path),
        ckpt_load_path=str(tmp_path),
        **{
            "MambaConfig.n_layer": 2,
            "MambaConfig.d_model": 64,
            "MambaConfig.d_intermediate": 96,
            "MambaConfig.vocab_size": 256,
            "MambaConfig.d_state": 16,
            "MambaConfig.headdim": 32,
            "MambaConfig.attn_layer_idx": (1,),
            "MambaConfig.chunk_size": 32,
        },
    )
    out = capsys.readouterr().out
    losses = _losses(out)
    assert losses and losses[-1] < losses[0], out[-2000:]
    assert os.path.isdir(tmp_path / "checkpoints" / "step_6_ckp")


def test_main_training_mixtral_entry(tmp_path, capsys):
    """The mixtral ENTRY: tiny MoE trains, reports the moe_drop_frac
    extra metric, and checkpoints."""
    import main_training_mixtral

    main_training_mixtral.main(
        use_dummy_dataset=True,
        num_steps=6,
        seq_length=64,
        batch_size=2,
        report_interval=3,
        checkpoint_interval=6,
        vocab_size=256,
        sharding_strategy="fsdp",
        ckpt_save_path=str(tmp_path),
        ckpt_load_path=str(tmp_path),
        **{
            "MixtralConfig.nlayers": 2,
            "MixtralConfig.emb_dim": 64,
            "MixtralConfig.nheads": 4,
            "MixtralConfig.kvheads": 2,
            "MixtralConfig.hidden_dim": 96,
            "MixtralConfig.num_experts": 4,
            "MixtralConfig.top_k": 2,
            "MixtralConfig.src_vocab_size": 256,
            "MixtralConfig.max_expected_seq_len": 64,
        },
    )
    out = capsys.readouterr().out
    losses = _losses(out)
    assert losses and losses[-1] < losses[0], out[-2000:]
    assert "moe_drop_frac" in out
    assert os.path.isdir(tmp_path / "checkpoints" / "step_6_ckp")


def test_main_training_dummy_and_resume(tmp_path, capsys):
    common = dict(
        model_variant="llama2_7b",
        use_dummy_dataset=True,
        seq_length=32,
        batch_size=2,
        report_interval=5,
        checkpoint_interval=10,
        vocab_size=256,
        sharding_strategy="hsdp",
        sharding_group_size=4,
        attention_kernel="xla",
        ckpt_save_path=str(tmp_path),
        ckpt_load_path=str(tmp_path),
        **TINY_OVERRIDES,
    )
    main_training_llama.main(num_steps=12, **common)
    out = capsys.readouterr().out
    assert "step: 10" in out
    assert os.path.isdir(tmp_path / "checkpoints" / "step_10_ckp")
    assert os.path.isdir(tmp_path / "checkpoints" / "step_12_ckp")
    losses = _losses(out)
    assert losses and losses[-1] < losses[0]

    # resume continues from step 12
    main_training_llama.main(num_steps=15, **common)
    out = capsys.readouterr().out
    assert "start_step = 12" in out
    assert "step: 15" in out
    assert os.path.isdir(tmp_path / "checkpoints" / "step_15_ckp")
