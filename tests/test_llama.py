"""Llama model unit tests: shapes, causality, scan/unroll equivalence,
variant registry parity with the reference table
(ref:fms_fsdp/utils/config_utils.py:25-161)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fms_fsdp_tpu.models.configs import LlamaConfig
from fms_fsdp_tpu.models.llama import init_llama_params, llama_forward, param_count
from fms_fsdp_tpu.utils.config_utils import get_model_config

TINY = LlamaConfig(
    src_vocab_size=257,
    emb_dim=64,
    nheads=4,
    kvheads=2,
    nlayers=4,
    hidden_grow_factor=8 / 3,
    multiple_of=16,
    max_expected_seq_len=64,
)


@pytest.fixture(scope="module")
def tiny_params():
    return init_llama_params(jax.random.PRNGKey(0), TINY)


def test_forward_shape_and_dtype(tiny_params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, TINY.src_vocab_size)
    logits = llama_forward(tiny_params, tokens, TINY, attn_impl="xla")
    assert logits.shape == (2, 16, TINY.src_vocab_size)
    assert logits.dtype == jnp.bfloat16  # compute dtype; loss upcasts
    assert np.isfinite(np.asarray(logits, dtype=np.float32)).all()


def test_causality(tiny_params):
    """Changing token t+k must not change logits at positions <= t."""
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (1, 16), 0, TINY.src_vocab_size)
    logits = llama_forward(
        tiny_params, tokens, TINY, attn_impl="xla", compute_dtype=jnp.float32
    )
    perturbed = tokens.at[0, 10].set((tokens[0, 10] + 1) % TINY.src_vocab_size)
    logits2 = llama_forward(
        tiny_params, perturbed, TINY, attn_impl="xla", compute_dtype=jnp.float32
    )
    np.testing.assert_allclose(logits[0, :10], logits2[0, :10], atol=1e-5)
    assert not np.allclose(logits[0, 10:], logits2[0, 10:])


def test_scan_unroll_equivalence(tiny_params):
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, TINY.src_vocab_size)
    a = llama_forward(
        tiny_params, tokens, TINY, scan_layers=True, compute_dtype=jnp.float32,
        attn_impl="xla",
    )
    b = llama_forward(
        tiny_params, tokens, TINY, scan_layers=False, compute_dtype=jnp.float32,
        attn_impl="xla",
    )
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_remat_matches_plain(tiny_params):
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 8), 0, TINY.src_vocab_size)

    def loss(params, mask):
        out = llama_forward(
            params, tokens, TINY, ac_mask=mask, compute_dtype=jnp.float32,
            attn_impl="xla",
        )
        return (out.astype(jnp.float32) ** 2).mean()

    g_plain = jax.grad(loss)(tiny_params, None)
    g_full = jax.grad(loss)(tiny_params, [True] * 4)
    g_frac = jax.grad(loss)(tiny_params, [False, True, False, True])
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_full)):
        np.testing.assert_allclose(a, b, atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_frac)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_variant_registry():
    """Spot-check the reference variant table's derived dimensions."""
    c7 = get_model_config("llama2_7b")
    assert (c7.emb_dim, c7.nheads, c7.n_kv_heads, c7.nlayers) == (4096, 32, 32, 32)
    assert c7.hidden_dim == 11008
    assert abs(c7.n_params() / 1e9 - 6.74) < 0.05

    c70 = get_model_config("llama2_70b")
    assert (c70.nheads, c70.n_kv_heads, c70.nlayers) == (64, 8, 80)
    assert c70.hidden_dim == 28672
    assert abs(c70.n_params() / 1e9 - 68.98) < 0.5

    c8b = get_model_config("llama3_8b")
    assert c8b.src_vocab_size == 128256
    assert c8b.hidden_dim == 14336
    assert c8b.rope_theta == 500000.0
    assert get_model_config("llama3_8b_4k").max_expected_seq_len == 4096

    c34 = get_model_config("llama2_34b")
    assert c34.max_expected_seq_len == 16384 and c34.rope_theta == 1000000.0

    with pytest.raises(ValueError):
        get_model_config("nope")


def test_param_count_matches_formula(tiny_params):
    assert param_count(tiny_params) == TINY.n_params()


def test_gqa_grouping(tiny_params):
    """GQA (kv < q heads) must differ from broadcasting value heads wrongly:
    just check kv head shapes flow and outputs are finite."""
    cfg = LlamaConfig(
        src_vocab_size=64, emb_dim=32, nheads=4, kvheads=1, nlayers=2, multiple_of=8
    )
    params = init_llama_params(jax.random.PRNGKey(5), cfg)
    assert params["layers"]["wk"].shape == (2, 32, 1 * 8)
    tokens = jnp.arange(12)[None, :] % 64
    out = llama_forward(params, tokens, cfg, attn_impl="xla")
    assert np.isfinite(np.asarray(out)).all()
