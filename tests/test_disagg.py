"""Disaggregated serving + sharded replicas (serve/disagg/,
ServeConfig.serve_layout; docs/serving.md "Sharded replicas &
disaggregation").

Anchors, per the PR-18 contract:

- PageHandoff wire bytes are deterministic ACROSS PROCESSES (canonical
  header JSON + sorted C-order leaves, pinned by a subprocess sha256
  under different PYTHONHASHSEEDs) and round-trip bit-exact for
  fp32/bf16/int8/fp8 page leaves — quantized pages ship as stored,
  never widened;
- unpacking into a FRESH pool preserves the allocator's reserved-page
  invariants: the zero page stays exactly zero (it is the bit-parity
  root every short sequence reads through) and the scratch page is
  untouched;
- greedy prefill->handoff->decode across two engines is token-for-token
  identical to one unified engine (llama and mixtral, plus quantized
  pools), and a decode-side eviction after import falls back to
  recompute-on-resume correctly;
- a tp/fsdp-sharded replica (serve_layout, multi-device CPU mesh via
  conftest's forced 8 devices) serves greedy streams token-identical to
  the single-chip engine, with params and KV pools actually sharded;
- the fleet router journals handoffs before forwarding (crash on either
  side of a half-shipped handoff requeues exactly-once), dispatches
  fresh rids to prefill replicas and handoff-carrying rids to decode
  replicas;
- mamba rejects layouts and non-unified roles with actionable errors;
- serving_stats carries the schema-v13 fields and validates.
"""

import base64
import hashlib
import json
import subprocess
import sys

import jax
import numpy as np
import pytest

from fms_fsdp_tpu.models.configs import LlamaConfig, MixtralConfig
from fms_fsdp_tpu.models.llama import init_llama_params
from fms_fsdp_tpu.models.mixtral import init_mixtral_params
from fms_fsdp_tpu.obs.schema import validate_record
from fms_fsdp_tpu.parallel.sharding import (
    parse_serve_layout,
    serve_layout_code,
)
from fms_fsdp_tpu.serve.disagg import (
    ROLE_CODES,
    HandoffError,
    pack_handoff,
    unpack_handoff,
)
from fms_fsdp_tpu.serve.engine import ServeConfig, ServingEngine
from fms_fsdp_tpu.serve.fleet import FleetConfig, FleetRouter
from fms_fsdp_tpu.serve.kv_cache import (
    RESERVED_PAGES,
    SCRATCH_PAGE,
    ZERO_PAGE,
    PagedKVCache,
)
from fms_fsdp_tpu.serve.scheduler import RequestRejected

TINY = LlamaConfig(
    src_vocab_size=128, emb_dim=64, nheads=4, kvheads=2, nlayers=2,
    max_expected_seq_len=256,
)
TINY_MIXTRAL = MixtralConfig(
    src_vocab_size=128, emb_dim=64, nheads=4, kvheads=2, nlayers=2,
    hidden_dim=128, num_experts=4, top_k=2, max_expected_seq_len=64,
)
PROMPTS = [[3, 5, 7], [11, 13, 17, 19], [2]]


@pytest.fixture(scope="module")
def tiny_params():
    return init_llama_params(jax.random.PRNGKey(0), TINY)


@pytest.fixture(scope="module")
def mixtral_params():
    return init_mixtral_params(jax.random.PRNGKey(2), TINY_MIXTRAL)


def _scfg(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("compute_dtype", "float32")
    kw.setdefault("attn_impl", "reference")
    kw.setdefault("page_size", 8)
    return ServeConfig(**kw)


def _serve_all(engine, prompts, max_new=6):
    reqs = [engine.submit(p, max_new) for p in prompts]
    engine.run()
    return reqs


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------


def _leaf(dtype, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(2, 3, 4).astype(np.float32)
    return a.astype(dtype)


@pytest.mark.parametrize(
    "dtype", ["float32", "bfloat16", "int8", "float8_e4m3fn"]
)
def test_pack_unpack_bit_exact_per_dtype(dtype):
    import ml_dtypes

    np_dtype = {
        "float32": np.float32,
        "bfloat16": ml_dtypes.bfloat16,
        "int8": np.int8,
        "float8_e4m3fn": ml_dtypes.float8_e4m3fn,
    }[dtype]
    arrays = {"k": _leaf(np_dtype, 0), "v": _leaf(np_dtype, 1)}
    header = {"family": "llama", "quant": "none", "seq_len": 3}
    wire = pack_handoff(header, arrays)
    h2, a2 = unpack_handoff(wire)
    assert h2["family"] == "llama" and h2["seq_len"] == 3
    for name in arrays:
        assert a2[name].dtype == np.dtype(np_dtype)
        # bit-exact: compare raw bytes, not values (NaN-safe, and the
        # contract is the STORAGE bits, not float equality)
        assert a2[name].tobytes() == np.ascontiguousarray(
            arrays[name]
        ).tobytes()


def test_pack_deterministic_across_processes(tmp_path):
    """Two fresh interpreters with different PYTHONHASHSEEDs must emit
    identical wire bytes for the same state — the canonical-JSON +
    sorted-leaf contract, not an accident of dict ordering."""
    prog = r"""
import hashlib, sys
import numpy as np
from fms_fsdp_tpu.serve.disagg import pack_handoff
arrays = {
    "v": (np.arange(24, dtype=np.float32) / 7).reshape(2, 3, 4),
    "k": (np.arange(24, dtype=np.float32) * 3).reshape(2, 3, 4),
}
header = {"zeta": 1, "alpha": [1, 2, 3], "quant": "none"}
sys.stdout.write(hashlib.sha256(pack_handoff(header, arrays)).hexdigest())
"""
    digests = set()
    for seed in ("0", "1", "31337"):
        out = subprocess.run(
            [sys.executable, "-c", prog],
            capture_output=True, text=True,
            env={
                "PYTHONHASHSEED": seed,
                "PATH": "/usr/bin:/bin",
                "PYTHONPATH": ":".join(sys.path),
            },
            check=True,
        )
        digests.add(out.stdout.strip())
    assert len(digests) == 1, digests


def test_unpack_rejects_corruption():
    wire = pack_handoff({"x": 1}, {"k": _leaf(np.float32)})
    with pytest.raises(HandoffError, match="magic"):
        unpack_handoff(b"NOPE" + wire[4:])
    with pytest.raises(HandoffError, match="checksum"):
        flipped = bytearray(wire)
        flipped[len(wire) // 2] ^= 0xFF
        unpack_handoff(bytes(flipped))
    with pytest.raises(HandoffError, match="magic"):
        unpack_handoff(b"FMSH")  # truncated below any valid frame
    with pytest.raises(HandoffError, match="checksum"):
        unpack_handoff(wire[:-5] + wire[-4:])  # torn leaf tail
    # version check: patch the u16 and re-crc
    import struct
    import zlib

    body = bytearray(wire[:-4])
    struct.pack_into("<H", body, 4, 99)
    bad = bytes(body) + struct.pack(
        "<I", zlib.crc32(bytes(body)) & 0xFFFFFFFF
    )
    with pytest.raises(HandoffError, match="version 99"):
        unpack_handoff(bad)


def test_scatter_into_fresh_pool_preserves_reserved_pages(tiny_params):
    """Gather a live sequence's pages, scatter into a FRESH pool: the
    landed values are bit-exact and the reserved pages keep their
    invariants — zero page exactly zero (the bit-parity root), scratch
    page untouched."""
    eng = ServingEngine(tiny_params, TINY, _scfg())
    _serve_all(eng, PROMPTS, max_new=4)
    # re-serve one stream and freeze it mid-flight to gather live pages
    req = eng.submit([23, 29, 31], 8)
    eng.step()  # prefilled, 1 token generated
    src = eng.cache
    gathered = src.gather_pages(req.rid)
    assert set(gathered) == set(src.pools)

    fresh = PagedKVCache(
        src.n_layers, src.num_pages, src.page_size, src.n_kv_heads,
        src.head_dim, dtype=src.pools["k"].dtype, quant=src.quant,
    )
    scratch_before = {
        n: np.asarray(p[:, SCRATCH_PAGE]) for n, p in fresh.pools.items()
    }
    ok = fresh.scatter_pages(req.rid, gathered, src.tokens_of(req.rid))
    assert ok
    for name, pool in fresh.pools.items():
        np.testing.assert_array_equal(
            np.asarray(pool[:, fresh._seq_pages[req.rid]]),
            np.asarray(gathered[name]),
        )
        assert not np.asarray(pool[:, ZERO_PAGE]).any(), (
            f"{name}: zero page dirtied by scatter"
        )
        np.testing.assert_array_equal(
            np.asarray(pool[:, SCRATCH_PAGE]), scratch_before[name]
        )
    assert fresh.tokens_of(req.rid) == src.tokens_of(req.rid)
    assert fresh.pages_in_use == len(fresh._seq_pages[req.rid])


# ---------------------------------------------------------------------------
# engine-level disaggregation
# ---------------------------------------------------------------------------


def _disagg_tokens(params, cfg, scfg_kw, prompts, max_new=6):
    pe = ServingEngine(params, cfg, _scfg(role="prefill", **scfg_kw))
    de = ServingEngine(params, cfg, _scfg(role="decode", **scfg_kw))
    preqs = _serve_all(pe, prompts, max_new)
    wires = [r.handoff_out for r in preqs]
    assert all(w is not None for w in wires)
    dreqs = [de.submit_handoff(w) for w in wires]
    de.run()
    return [list(r.generated) for r in dreqs], wires, pe, de


@pytest.mark.parametrize("kv_quant", ["none", "int8", "fp8"])
def test_disagg_greedy_parity_llama(tiny_params, kv_quant):
    kw = {"kv_quant": kv_quant}
    uni = ServingEngine(tiny_params, TINY, _scfg(**kw))
    baseline = [
        list(r.generated) for r in _serve_all(uni, PROMPTS)
    ]
    got, wires, pe, de = _disagg_tokens(tiny_params, TINY, kw, PROMPTS)
    assert got == baseline
    if kv_quant != "none":
        # quantized pages ship quantized: scale leaves present on the
        # wire, and the page leaf is the 1-byte storage dtype
        h, arrays = unpack_handoff(wires[0])
        assert h["quant"] == kv_quant
        assert {"k", "v", "k_scale", "v_scale"} == set(arrays)
        assert arrays["k"].dtype.itemsize == 1


def test_disagg_greedy_parity_mixtral(mixtral_params):
    kw = {"page_size": 16, "moe_impl": "dense"}
    uni = ServingEngine(mixtral_params, TINY_MIXTRAL, _scfg(**kw))
    baseline = [list(r.generated) for r in _serve_all(uni, PROMPTS)]
    got, _, _, _ = _disagg_tokens(
        mixtral_params, TINY_MIXTRAL, kw, PROMPTS
    )
    assert got == baseline


def test_disagg_wire_deterministic_and_restartable(tiny_params):
    """The same prefill twice emits identical bytes, and the SAME wire
    bytes resumed on two decode engines yield identical streams — the
    property the router's journaled-requeue replay rides on."""
    _, wires1, _, _ = _disagg_tokens(tiny_params, TINY, {}, PROMPTS)
    _, wires2, _, _ = _disagg_tokens(tiny_params, TINY, {}, PROMPTS)
    assert wires1 == wires2
    d1 = ServingEngine(tiny_params, TINY, _scfg(role="decode"))
    d2 = ServingEngine(tiny_params, TINY, _scfg(role="decode"))
    r1 = d1.submit_handoff(wires1[1])
    r2 = d2.submit_handoff(wires1[1])
    d1.run()
    d2.run()
    assert list(r1.generated) == list(r2.generated)


def test_decode_side_eviction_recomputes_after_import(tiny_params):
    """After a handoff import, eviction falls back to the standard
    recompute-on-resume (handoff_in was consumed): the stream still
    finishes with the unified engine's tokens."""
    uni = ServingEngine(tiny_params, TINY, _scfg(max_batch=2))
    baseline = [
        list(r.generated) for r in _serve_all(uni, PROMPTS[:2], 8)
    ]
    pe = ServingEngine(tiny_params, TINY, _scfg(role="prefill"))
    preqs = _serve_all(pe, PROMPTS[:2], 8)
    # tiny pool: 2 slots' worst case cannot coexist -> evictions
    de = ServingEngine(
        tiny_params, TINY,
        _scfg(role="decode", max_batch=2, num_pages=2 + RESERVED_PAGES),
    )
    dreqs = [de.submit_handoff(r.handoff_out) for r in preqs]
    de.run()
    assert [list(r.generated) for r in dreqs] == baseline
    assert de.scheduler.evicted >= 1, "pool was sized to force eviction"


def test_handoff_header_mismatch_is_typed(tiny_params):
    pe = ServingEngine(tiny_params, TINY, _scfg(role="prefill"))
    wire = _serve_all(pe, [PROMPTS[0]])[0].handoff_out
    de = ServingEngine(
        tiny_params, TINY, _scfg(role="decode", page_size=16)
    )
    with pytest.raises(HandoffError, match="page_size"):
        de.submit_handoff(wire)


def test_prefill_handoff_max_bytes_rejects_typed(tiny_params):
    pe = ServingEngine(
        tiny_params, TINY, _scfg(role="prefill", handoff_max_bytes=64)
    )
    with pytest.raises(RequestRejected) as ei:
        pe.submit(list(range(32)), 4)
    assert ei.value.reason == "too_large"
    assert "handoff_max_bytes" in str(ei.value)


def test_mamba_roles_and_layout_gates():
    from fms_fsdp_tpu.models.configs import MambaConfig
    from fms_fsdp_tpu.models.mamba import init_mamba_params

    cfg = MambaConfig(
        d_model=64, n_layer=2, vocab_size=128, d_state=16, headdim=16,
        chunk_size=8, attn_layer_idx=(), d_intermediate=128,
    )
    params = init_mamba_params(jax.random.PRNGKey(0), cfg)
    # mamba ships its recurrent state via the slab codec now: disagg
    # roles construct (full parity is pinned in tests/test_transport.py)
    pe = ServingEngine(params, cfg, _scfg(role="prefill", kv_quant="none"))
    assert pe.adapter.supports_handoff
    with pytest.raises(ValueError, match="single-chip"):
        ServingEngine(
            params, cfg, _scfg(serve_layout="tp=2", kv_quant="none")
        )
    with pytest.raises(ValueError, match="unknown serving role"):
        ServingEngine(params, cfg, _scfg(role="prefix"))


# ---------------------------------------------------------------------------
# sharded replicas (serve_layout on the 8-device CPU mesh)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("layout", ["tp=2", "tp=2,fsdp=2"])
def test_sharded_replica_token_parity(tiny_params, layout):
    uni = ServingEngine(tiny_params, TINY, _scfg())
    baseline = [list(r.generated) for r in _serve_all(uni, PROMPTS)]
    sh = ServingEngine(tiny_params, TINY, _scfg(serve_layout=layout))
    got = [list(r.generated) for r in _serve_all(sh, PROMPTS)]
    assert got == baseline
    n_dev = parse_serve_layout(layout)
    n_dev = n_dev["tensor"] * n_dev["fsdp"]
    assert sh.adapter.mesh is not None
    assert len(sh.adapter.mesh.devices.flat) == n_dev
    # params actually span the mesh (wq sharded over its device set)
    wq = sh.adapter.params["layers"]["wq"]
    assert len(wq.sharding.device_set) == n_dev
    # KV pools sharded over the kv-head axis on the tensor dim
    k = sh.cache.pools["k"]
    assert len(k.sharding.device_set) == n_dev


def test_sharded_mixtral_token_parity(mixtral_params):
    kw = {"page_size": 16, "moe_impl": "dense"}
    uni = ServingEngine(mixtral_params, TINY_MIXTRAL, _scfg(**kw))
    baseline = [list(r.generated) for r in _serve_all(uni, PROMPTS)]
    sh = ServingEngine(
        mixtral_params, TINY_MIXTRAL, _scfg(serve_layout="tp=2", **kw)
    )
    got = [list(r.generated) for r in _serve_all(sh, PROMPTS)]
    assert got == baseline


def test_sharded_disagg_compose(tiny_params):
    """Layout and role compose: a sharded prefill engine hands off to a
    sharded decode engine, token-identical to single-chip unified."""
    uni = ServingEngine(tiny_params, TINY, _scfg())
    baseline = [list(r.generated) for r in _serve_all(uni, PROMPTS)]
    got, _, _, _ = _disagg_tokens(
        tiny_params, TINY, {"serve_layout": "tp=2"}, PROMPTS
    )
    assert got == baseline


def test_parse_serve_layout_contract():
    assert parse_serve_layout("") == {"tensor": 1, "fsdp": 1}
    assert parse_serve_layout("tp=2") == {"tensor": 2, "fsdp": 1}
    assert parse_serve_layout("tp=2,fsdp=4") == {"tensor": 2, "fsdp": 4}
    assert serve_layout_code("") == 0
    assert serve_layout_code("tp=2") == 201
    assert serve_layout_code("tp=2,fsdp=2") == 202
    with pytest.raises(ValueError, match="unknown serve_layout axis"):
        parse_serve_layout("dp=2")
    with pytest.raises(ValueError):
        parse_serve_layout("tp=0")


# ---------------------------------------------------------------------------
# obs schema v13
# ---------------------------------------------------------------------------


def test_serving_stats_v13_fields_validate(tiny_params):
    got, wires, pe, de = _disagg_tokens(tiny_params, TINY, {}, PROMPTS)
    for eng, role in ((pe, "prefill"), (de, "decode")):
        st = eng.serving_stats()
        assert st["role"] == float(ROLE_CODES[role])
        assert st["serve_layout"] == 0.0
        assert st["handoff_bytes"] == float(sum(len(w) for w in wires))
        assert st["handoff_s"] >= 0.0
    sh = ServingEngine(
        tiny_params, TINY, _scfg(serve_layout="tp=2,fsdp=2")
    )
    assert sh.serving_stats()["serve_layout"] == 202.0
    # a full obs record carrying the v13 serving map validates
    from tests.test_obs import _observer_record

    rec = _observer_record()
    rec["serving"] = de.serving_stats()
    assert validate_record(rec) == []


# ---------------------------------------------------------------------------
# fleet router disaggregation (fake replicas; subprocess e2e lives in
# scripts/chaos_soak_serving.py --disagg)
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class DisaggFakeReplica:
    """Role-aware in-process replica double. Prefill role answers each
    submit with a ``handoff`` after ``steps_per_req`` ticks; decode
    role only accepts ``resume`` and emits ``done``."""

    def __init__(self, ctx, role, steps_per_req=2):
        self.ctx = ctx
        self.role = role
        self.out = [{"type": "hb", "iterations": 0, "completed": 0,
                     "slots_busy": 0, "queue_depth": 0}]
        self.dead = None
        self.work = {}
        self.completed = 0
        self.steps_per_req = steps_per_req
        self.got_msgs = []

    def send(self, msg):
        if self.dead is not None:
            return False
        self.got_msgs.append(msg)
        if msg["type"] == "submit":
            assert self.role == "prefill", (
                f"fresh rid routed to a {self.role} replica"
            )
            self.work[msg["rid"]] = [
                self.steps_per_req, msg["max_new_tokens"], None,
            ]
        elif msg["type"] == "resume":
            assert self.role == "decode", (
                f"handoff routed to a {self.role} replica"
            )
            self.work[msg["rid"]] = [
                self.steps_per_req, msg["max_new_tokens"], msg["data"],
            ]
        return True

    def tick(self):
        if self.dead is not None:
            return
        for rid, st in list(self.work.items()):
            st[0] -= 1
            if st[0] <= 0:
                self.completed += 1
                if self.role == "prefill":
                    data = base64.b64encode(
                        f"pages-of-{rid}".encode()
                    ).decode("ascii")
                    self.out.append({
                        "type": "handoff", "rid": rid, "data": data,
                        "bytes": len(data), "ttft": 0.25,
                    })
                else:
                    self.out.append({
                        "type": "done", "rid": rid,
                        "tokens": list(range(st[1])), "ttft": 9.9,
                    })
                del self.work[rid]
        self.out.append({"type": "hb", "iterations": 1,
                         "completed": self.completed,
                         "slots_busy": len(self.work), "queue_depth": 0})

    def recv(self):
        o, self.out = self.out, []
        return o

    def drain_final(self, timeout_s=1.0):
        return self.recv()

    def poll(self):
        return self.dead

    def kill(self):
        self.dead = -9

    def close(self):
        pass


def _disagg_fleet(clk, n=3, prefill=1, **cfg_kw):
    replicas = {}

    def spawn(ctx):
        role = "prefill" if ctx["replica"] < prefill else "decode"
        r = DisaggFakeReplica(ctx, role)
        replicas[ctx["replica"]] = r
        return r

    cfg_kw.setdefault("n_replicas", n)
    cfg_kw.setdefault("prefill_replicas", prefill)
    cfg_kw.setdefault("max_seq_len", 64)
    cfg_kw.setdefault("max_inflight_per_replica", 4)
    cfg_kw.setdefault("stall_timeout_s", 5.0)
    cfg_kw.setdefault("restart_backoff_s", 0.1)
    router = FleetRouter(
        spawn, FleetConfig(**cfg_kw), clock=clk, log=lambda m: None
    )
    return router, replicas


def _drive(router, replicas, clk, ticks, dt=0.5, on_tick=None):
    done = []
    for i in range(ticks):
        clk.t += dt
        for r in replicas.values():
            r.tick()
        if on_tick:
            on_tick(i)
        done += router.poll()
    return done


def test_router_disagg_happy_path_roles_and_journal(tmp_path):
    clk = FakeClock()
    router, replicas = _disagg_fleet(
        clk, journal_path=str(tmp_path / "j.jsonl")
    )
    router.start()
    rids = [router.submit([1, 2, 3], 4) for _ in range(6)]
    done = _drive(router, replicas, clk, 40)
    assert sorted(r.rid for r in done) == rids
    s = router.stats()
    assert s["completion_rate"] == 1.0
    assert s["requests_handed_off"] == 6.0
    assert s["prefill_replicas"] == 1.0
    # every fresh rid hit the prefill replica, every resume a decode one
    assert all(
        m["type"] in ("submit", "drain")
        for m in replicas[0].got_msgs
    )
    resumes = [
        m for i in (1, 2) for m in replicas[i].got_msgs
        if m["type"] == "resume"
    ]
    assert len(resumes) == 6
    # handoff TTFT (prefill side) survives onto the completed record,
    # the decode side's does not overwrite it
    assert all(
        router.journal.records[r].engine_ttft == 0.25 for r in rids
    )
    # journaled handoff events precede completion; bytes cleared after
    events = [json.loads(l)["event"] for l in open(tmp_path / "j.jsonl")]
    assert events.count("handoff") == 6
    assert all(
        router.journal.records[r].handoff is None for r in rids
    )
    assert all(
        router.journal.records[r].handoff_bytes > 0 for r in rids
    )


def test_router_prefill_death_mid_handoff_requeues_prompt(tmp_path):
    """The prefill worker dies BEFORE its handoff escapes: no bytes
    were journaled, so the rid requeues as a fresh prompt and
    re-prefills on the relaunched incarnation. Zero drops."""
    clk = FakeClock()
    router, replicas = _disagg_fleet(clk)
    router.start()
    rids = [router.submit([1, 2, 3], 4) for _ in range(4)]

    def kill_prefill(i):
        if i == 1:
            # drop its un-emitted work and die: handoffs never escape
            replicas[0].work.clear()
            replicas[0].out = []
            replicas[0].dead = 10

    done = _drive(router, replicas, clk, 60, on_tick=kill_prefill)
    assert sorted(r.rid for r in done) == rids
    s = router.stats()
    assert s["completion_rate"] == 1.0
    assert s["requests_requeued"] >= 1
    assert s["restarts"] >= 1
    # the requeued rids re-prefilled: no handoff was journaled for them
    # at requeue time (rec.handoff was still None)
    assert router.journal.duplicates_dropped == 0


def test_router_decode_death_post_handoff_replays_bytes():
    """The decode replica dies AFTER the handoff was journaled: the
    requeue keeps the bytes, and the replay goes to a decode sibling as
    a ``resume`` carrying the SAME wire bytes — the prefill worker is
    never re-consulted."""
    clk = FakeClock()
    router, replicas = _disagg_fleet(clk, n=3, prefill=1)
    router.start()
    rids = [router.submit([1, 2, 3], 4) for _ in range(4)]
    seen_data = {}

    def snoop_then_kill(i):
        # once replica 1 (decode) owns resumed work, kill it
        if replicas[1].work and replicas[1].dead is None:
            for rid, st in replicas[1].work.items():
                seen_data[rid] = st[2]
            replicas[1].dead = 10

    done = _drive(router, replicas, clk, 80, on_tick=snoop_then_kill)
    assert sorted(r.rid for r in done) == rids
    assert seen_data, "the kill never fired on owned decode work"
    s = router.stats()
    assert s["completion_rate"] == 1.0
    assert s["requests_requeued"] >= 1
    # the replayed resume carried the journaled bytes verbatim
    prefill_submits = [
        m["rid"] for m in replicas[0].got_msgs if m["type"] == "submit"
    ]
    assert sorted(set(prefill_submits)) == rids, (
        "a decode-side death must not re-prefill"
    )
    assert len(prefill_submits) == len(rids)
    replayed = [
        m for r in replicas.values() for m in r.got_msgs
        if m["type"] == "resume" and m["rid"] in seen_data
    ]
    for m in replayed:
        assert m["data"] == seen_data[m["rid"]]


def test_router_head_of_line_waits_for_role_pool():
    """A fresh rid with the prefill pool down waits (no bypass to
    decode replicas), and dispatches the moment the pool relaunches."""
    clk = FakeClock()
    router, replicas = _disagg_fleet(clk, n=2, prefill=1)
    router.start()
    clk.t += 0.5
    for r in replicas.values():
        r.tick()
    router.poll()  # both ready
    replicas[1].dead = None  # keep decode alive
    replicas[0].dead = 10  # prefill pool down
    rid = router.submit([1, 2, 3], 4)
    clk.t += 0.5
    router.poll()  # death sweep; nothing dispatchable
    assert router.journal.records[rid].state == "queued"
    assert all(
        m["type"] != "submit" for m in replicas[1].got_msgs
    ), "fresh rid must not bypass to a decode replica"
    done = _drive(router, replicas, clk, 40)
    assert [r.rid for r in done] == [rid]


def test_fleet_config_prefill_bounds():
    with pytest.raises(ValueError, match="prefill_replicas"):
        FleetRouter(
            lambda ctx: None,
            FleetConfig(n_replicas=2, prefill_replicas=2),
            log=lambda m: None,
        )
