"""Mesh + sharding + end-to-end train-step tests on the 8-device CPU mesh.

Verifies the jax.sharding replacement for the reference's FSDP/HSDP/DDP
trichotomy (ref:train_utils.py:227-234): mesh shapes, param placement, and
that the full jitted train step runs and learns under each strategy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from fms_fsdp_tpu.config import TrainConfig
from fms_fsdp_tpu.models.configs import LlamaConfig
from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh
from fms_fsdp_tpu.parallel.sharding import (
    infer_state_specs,
    llama_param_specs,
    resolve_spec,
)
from fms_fsdp_tpu.train.step import (
    init_train_state,
    make_optimizer,
    make_train_step,
)

TINY = LlamaConfig(
    src_vocab_size=256,
    emb_dim=64,
    nheads=4,
    kvheads=2,
    nlayers=2,
    multiple_of=16,
    max_expected_seq_len=64,
)


def _cfg(**kw):
    base = dict(
        model_variant="tiny",
        seq_length=16,
        batch_size=2,
        num_steps=100,
        learning_rate=1e-2,
        report_interval=10,
        vocab_size=256,
        attention_kernel="xla",
    )
    base.update(kw)
    return TrainConfig(**base)


def _shape(**kw):
    base = {
        "dcn": 1,
        "replica": 1,
        "fsdp": 1,
        "expert": 1,
        "context": 1,
        "tensor": 1,
    }
    base.update(kw)
    return base


def test_mesh_shapes():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    m = build_mesh(MeshConfig(sharding_strategy="fsdp"))
    assert dict(m.shape) == _shape(fsdp=8)
    m = build_mesh(MeshConfig(sharding_strategy="ddp"))
    assert dict(m.shape) == _shape(replica=8)
    m = build_mesh(MeshConfig(sharding_strategy="hsdp", sharding_group_size=4))
    assert dict(m.shape) == _shape(replica=2, fsdp=4)
    m = build_mesh(MeshConfig(sharding_strategy="fsdp", tensor_parallel_size=2))
    assert dict(m.shape) == _shape(fsdp=4, tensor=2)
    m = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )
    assert dict(m.shape) == _shape(fsdp=4, context=2)
    m = build_mesh(
        MeshConfig(sharding_strategy="fsdp", expert_parallel_size=4)
    )
    assert dict(m.shape) == _shape(fsdp=2, expert=4)
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(sharding_strategy="hsdp", sharding_group_size=3))


# ---- multi-slice (dcn axis) -------------------------------------------------


def test_multislice_mesh_shapes():
    """The dcn axis takes the cross-slice factor; strategies split the
    PER-SLICE data-parallel extent."""
    m = build_mesh(MeshConfig(sharding_strategy="fsdp", num_slices=2))
    assert dict(m.shape) == _shape(dcn=2, fsdp=4)
    m = build_mesh(
        MeshConfig(sharding_strategy="hsdp", num_slices=2, sharding_group_size=2)
    )
    assert dict(m.shape) == _shape(dcn=2, replica=2, fsdp=2)
    m = build_mesh(
        MeshConfig(
            sharding_strategy="fsdp", num_slices=2, tensor_parallel_size=2
        )
    )
    assert dict(m.shape) == _shape(dcn=2, fsdp=2, tensor=2)
    # each dcn index holds one slice's devices (contiguous blocks on the
    # simulated partition)
    m2 = build_mesh(MeshConfig(sharding_strategy="fsdp", num_slices=2))
    ids = np.vectorize(lambda d: d.id)(m2.devices)
    assert sorted(ids[0].flatten().tolist()) == [0, 1, 2, 3]
    assert sorted(ids[1].flatten().tolist()) == [4, 5, 6, 7]
    with pytest.raises(ValueError, match="slice"):
        build_mesh(MeshConfig(sharding_strategy="fsdp", num_slices=3))


def test_single_slice_mesh_is_legacy_5axis_placement():
    """dcn=1 meshes are the historical 5-axis construction with a
    leading size-1 axis reshaped on: device placement is bit-identical
    for every strategy (elastic fingerprints, checkpoint shardings, and
    collective replica groups all hang off this)."""
    from jax.experimental import mesh_utils

    for cfg, shape5 in [
        (MeshConfig(sharding_strategy="fsdp"), (1, 8, 1, 1, 1)),
        (MeshConfig(sharding_strategy="ddp"), (8, 1, 1, 1, 1)),
        (
            MeshConfig(sharding_strategy="hsdp", sharding_group_size=4),
            (2, 4, 1, 1, 1),
        ),
        (
            MeshConfig(sharding_strategy="fsdp", tensor_parallel_size=2),
            (1, 4, 1, 1, 2),
        ),
    ]:
        m = build_mesh(cfg)
        legacy = mesh_utils.create_device_mesh(shape5, devices=jax.devices())
        got = np.vectorize(lambda d: d.id)(m.devices)
        want = np.vectorize(lambda d: d.id)(legacy)[None]
        assert (got == want).all(), (cfg, got, want)


def test_default_group_size_from_passed_devices():
    """Satellite fix: HSDP group inference derives devices-per-host from
    the PASSED devices (and their slice membership), never from this
    process's jax.local_device_count() — a simulated/partial world must
    get groups for ITS shape."""
    from fms_fsdp_tpu.parallel.mesh import _default_group_size

    class FakeDev:
        def __init__(self, process_index):
            self.process_index = process_index

    # 2 hosts x 4 devices: shard within the 4-device host. The old code
    # consulted jax.local_device_count() (8 on this test backend) and
    # would have returned 8 — one group spanning both hosts.
    two_hosts = [FakeDev(p) for p in (0, 0, 0, 0, 1, 1, 1, 1)]
    assert _default_group_size(8, two_hosts) == 4
    # single host: no multi-host split -> the full extent
    assert _default_group_size(4, [FakeDev(0)] * 4) == 4
    # non-dividing host size degrades to the full extent
    assert _default_group_size(6, [FakeDev(0)] * 4 + [FakeDev(1)] * 2) == 6


def test_slice_assignments_and_context():
    from fms_fsdp_tpu.parallel.mesh import (
        process_slice_context,
        slice_assignments,
    )

    ids, n = slice_assignments(jax.devices())
    assert n == 1 and set(ids) == {0}
    ids, n = slice_assignments(jax.devices(), 2)
    assert n == 2 and ids == [0, 0, 0, 0, 1, 1, 1, 1]
    with pytest.raises(ValueError, match="slice"):
        slice_assignments(jax.devices(), 3)
    # single-process world: this process is always slice 0
    assert process_slice_context() == (1, 0)

    class Cfg:
        num_slices = 2

    assert process_slice_context(Cfg()) == (2, 0)


def test_hierarchical_reduce_info():
    from fms_fsdp_tpu.parallel.sharding import hierarchical_reduce_info

    m1 = build_mesh(MeshConfig(sharding_strategy="fsdp"))
    info = hierarchical_reduce_info(m1)
    assert info == {"ici_axes": ("fsdp",), "dcn_axes": ()}
    m2 = build_mesh(
        MeshConfig(sharding_strategy="hsdp", num_slices=2, sharding_group_size=2)
    )
    info = hierarchical_reduce_info(m2)
    assert info == {"ici_axes": ("replica", "fsdp"), "dcn_axes": ("dcn",)}


def test_resolve_spec_drops_axes_missing_from_mesh():
    """A 5-axis legacy mesh consumes the shared dcn-bearing specs: axes
    the mesh does not carry resolve away instead of KeyError-ing."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from fms_fsdp_tpu.parallel.sharding import batch_pspec

    legacy = Mesh(
        mesh_utils.create_device_mesh((1, 8, 1, 1, 1), devices=jax.devices()),
        ("replica", "fsdp", "expert", "context", "tensor"),
    )
    spec = resolve_spec(batch_pspec(), (8, 64), legacy)
    assert spec == P(("replica", "fsdp", "expert"), "context")


def _compiled_step_text(cfg, mesh):
    import jax.numpy as jnp

    opt = make_optimizer(cfg)
    state, _ = init_train_state(jax.random.PRNGKey(0), TINY, cfg, mesh, opt)
    step_fn = make_train_step(TINY, cfg, mesh, opt)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(8, 17))
    batch = (
        jnp.asarray(tokens[:, :-1], jnp.int32),
        jnp.asarray(tokens[:, 1:], jnp.int32),
    )
    return (
        jax.jit(lambda s, b: step_fn(s, b)).lower(state, batch).compile()
        .as_text(),
        batch,
    )


def test_dcn1_step_adds_no_collectives():
    """The bit-identity pin (same technique class as the quant suite's
    no-narrow-types scan): the compiled train step on a dcn=1 mesh
    carries exactly the collectives of the legacy 5-axis program — no
    cross-slice op, and no extra within-slice op either."""
    import re

    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from fms_fsdp_tpu.parallel.mesh import hlo_collective_split

    cfg = _cfg(sharding_strategy="fsdp")
    m6 = build_mesh(MeshConfig.from_train_config(cfg))
    legacy = Mesh(
        mesh_utils.create_device_mesh((1, 8, 1, 1, 1), devices=jax.devices()),
        ("replica", "fsdp", "expert", "context", "tensor"),
    )
    txt6, _ = _compiled_step_text(cfg, m6)
    txt5, _ = _compiled_step_text(cfg, legacy)

    def collective_lines(t):
        return sorted(
            re.findall(
                r"\b(?:all-reduce|all-gather|reduce-scatter|all-to-all|"
                r"collective-permute)(?:-start)?[.\d]*\([^\n]*",
                t,
            )
        )

    assert collective_lines(txt6) == collective_lines(txt5)
    split = hlo_collective_split(txt6, m6)
    assert split["dcn"] == 0 and split["unattributed"] == 0, split


def test_two_slice_step_has_dcn_collectives_and_agrees():
    """Positive control for the dcn=1 pin: a 2-slice mesh's compiled
    step really does carry cross-slice collectives — and the math is
    the same (first-steps loss matches single-slice fsdp)."""
    import jax.numpy as jnp

    from fms_fsdp_tpu.parallel.mesh import hlo_collective_split

    cfg2 = _cfg(sharding_strategy="fsdp", num_slices=2)
    m2 = build_mesh(MeshConfig.from_train_config(cfg2))
    txt2, batch = _compiled_step_text(cfg2, m2)
    split = hlo_collective_split(txt2, m2)
    assert split["dcn"] > 0, split

    results = {}
    for name, cfg in (("slice2", cfg2), ("fsdp", _cfg(sharding_strategy="fsdp"))):
        mesh = build_mesh(MeshConfig.from_train_config(cfg))
        opt = make_optimizer(cfg)
        state, _ = init_train_state(
            jax.random.PRNGKey(0), TINY, cfg, mesh, opt
        )
        step_fn = make_train_step(TINY, cfg, mesh, opt)
        for _ in range(3):
            state, metrics = step_fn(state, batch)
        results[name] = float(metrics["loss"])
    assert results["slice2"] == pytest.approx(results["fsdp"], rel=2e-2)


def test_resolve_spec_divisibility():
    mesh = build_mesh(MeshConfig(sharding_strategy="fsdp"))
    # 64 divisible by 8 -> kept; 30 not -> dropped
    assert resolve_spec(P("fsdp", None), (64, 3), mesh) == P("fsdp", None)
    assert resolve_spec(P("fsdp", None), (30, 3), mesh) == P(None, None)


def test_llama70b_shardings_resolve():
    """The 70B target config (GQA 64/8 heads, emb 8192) produces valid
    NamedShardings for the full train state on an 8-device FSDP mesh —
    shape-level only (eval_shape; nothing materialized)."""
    from fms_fsdp_tpu.parallel.sharding import tree_shardings
    from fms_fsdp_tpu.train.step import make_optimizer
    from fms_fsdp_tpu.utils.config_utils import get_model_config

    cfg = TrainConfig(sharding_strategy="fsdp", seq_length=4096)
    model_cfg = get_model_config("llama2_70b")
    assert model_cfg.nheads == 64 and model_cfg.n_kv_heads == 8
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)

    from fms_fsdp_tpu.models import get_model_api

    init_params, _, specs_fn, _ = get_model_api(model_cfg)

    def init_fn(rng):
        params = init_params(rng, model_cfg, dtype=jnp.float32)
        return {
            "params": params,
            "opt_state": opt.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    n_params = sum(
        np.prod(s.shape) for s in jax.tree.leaves(shapes["params"])
    )
    assert n_params > 65e9  # truly 70B-scale
    specs = infer_state_specs(shapes, specs_fn())
    shardings = tree_shardings(
        mesh, specs, jax.tree.map(lambda s: s.shape, shapes)
    )
    # every leaf resolves; the big 2D weights actually shard over fsdp
    for leaf in jax.tree.leaves(shardings):
        assert leaf is not None
    assert "fsdp" in str(shardings["params"]["layers"]["wq"].spec)


def test_state_spec_inference():
    cfg = _cfg(sharding_strategy="fsdp")
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    state, shardings = init_train_state(
        jax.random.PRNGKey(0), TINY, cfg, mesh, opt
    )
    # params sharded over fsdp on the model dim
    wq_spec = state["params"]["layers"]["wq"].sharding.spec
    assert wq_spec[1] == "fsdp"
    # adam mu mirrors the param sharding
    mu = state["opt_state"].inner_state[0].mu["layers"]["wq"]
    assert mu.sharding.spec == state["params"]["layers"]["wq"].sharding.spec
    # scalar step replicated
    assert state["step"].sharding.spec == P()


@pytest.mark.parametrize(
    "strategy,extra",
    [
        ("ddp", {}),
        ("fsdp", {}),
        ("hsdp", {"sharding_group_size": 4}),
        ("fsdp", {"tensor_parallel_size": 2}),
    ],
)
def test_train_step_learns(strategy, extra):
    cfg = _cfg(sharding_strategy=strategy, **{k: v for k, v in extra.items()})
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    state, _ = init_train_state(jax.random.PRNGKey(0), TINY, cfg, mesh, opt)
    step_fn = make_train_step(TINY, cfg, mesh, opt)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(8, 17))
    inputs = jnp.asarray(tokens[:, :-1], jnp.int32)
    labels = jnp.asarray(tokens[:, 1:], jnp.int32)
    labels = labels.at[:, 0].set(-100)  # causal_lm prompt masking analog

    losses = []
    for _ in range(20):
        state, metrics = step_fn(state, (inputs, labels))
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    # memorizing one batch must drive loss down hard
    assert losses[-1] < losses[0] * 0.5, losses
    assert float(metrics["gnorm"]) > 0
    assert int(state["step"]) == 20


def test_strategies_agree():
    """ddp and fsdp are the same math — first-step loss must match."""
    results = {}
    for strategy in ["ddp", "fsdp"]:
        cfg = _cfg(sharding_strategy=strategy)
        mesh = build_mesh(MeshConfig.from_train_config(cfg))
        opt = make_optimizer(cfg)
        state, _ = init_train_state(jax.random.PRNGKey(0), TINY, cfg, mesh, opt)
        step_fn = make_train_step(TINY, cfg, mesh, opt)
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, 256, size=(8, 17))
        inputs = jnp.asarray(tokens[:, :-1], jnp.int32)
        labels = jnp.asarray(tokens[:, 1:], jnp.int32)
        for _ in range(3):
            state, metrics = step_fn(state, (inputs, labels))
        results[strategy] = float(metrics["loss"])
    assert results["ddp"] == pytest.approx(results["fsdp"], rel=2e-2)


def test_base_api_specs_shard_every_arch():
    """Every speculator base arch must ship a spec rulebook so a large
    frozen base is never silently replicated
    (ref:speculator/train_speculator.py:133-160 shards all bases). Big
    weight matrices land sharded, and the sharded forward matches the
    host-side forward."""
    from fms_fsdp_tpu.models import get_base_api
    from fms_fsdp_tpu.models.configs import MixtralConfig
    from fms_fsdp_tpu.models.gpt_bigcode import GPTBigCodeConfig
    from fms_fsdp_tpu.parallel.sharding import shard_params

    cfg = _cfg(sharding_strategy="fsdp")
    mesh = build_mesh(MeshConfig.from_train_config(cfg))

    arch_cfgs = {
        "llama": TINY,
        "gpt_bigcode": GPTBigCodeConfig(
            src_vocab_size=256,
            emb_dim=64,
            nheads=4,
            nlayers=2,
            max_expected_seq_len=64,
        ),
        "mixtral": MixtralConfig(
            src_vocab_size=256,
            emb_dim=64,
            nheads=4,
            kvheads=2,
            nlayers=2,
            hidden_dim=96,
            num_experts=4,
            top_k=2,
            max_expected_seq_len=64,
        ),
    }
    # per arch: one big matrix leaf that MUST be sharded on an fsdp mesh
    must_shard = {
        "llama": lambda p: p["layers"]["w1"],
        "gpt_bigcode": lambda p: p["layers"]["c_fc"],
        "mixtral": lambda p: p["layers"]["w1"],
    }
    for arch, mc in arch_cfgs.items():
        api = get_base_api(arch)
        assert api.param_specs is not None, arch
        params = api.init(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
        logits_host, _ = api.forward_embeds(
            params,
            jnp.zeros((1, 8), jnp.int32),
            mc,
            compute_dtype=jnp.float32,
        )
        sharded = shard_params(params, api.param_specs(), mesh)
        leaf = must_shard[arch](sharded)
        assert not leaf.sharding.is_fully_replicated, (
            arch,
            leaf.shape,
            leaf.sharding,
        )
        logits_dev, _ = api.forward_embeds(
            sharded,
            jnp.zeros((1, 8), jnp.int32),
            mc,
            compute_dtype=jnp.float32,
        )
        np.testing.assert_allclose(
            np.asarray(logits_dev), np.asarray(logits_host), atol=2e-4
        )
