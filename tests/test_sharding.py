"""Mesh + sharding + end-to-end train-step tests on the 8-device CPU mesh.

Verifies the jax.sharding replacement for the reference's FSDP/HSDP/DDP
trichotomy (ref:train_utils.py:227-234): mesh shapes, param placement, and
that the full jitted train step runs and learns under each strategy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from fms_fsdp_tpu.config import TrainConfig
from fms_fsdp_tpu.models.configs import LlamaConfig
from fms_fsdp_tpu.parallel.mesh import MeshConfig, build_mesh
from fms_fsdp_tpu.parallel.sharding import (
    infer_state_specs,
    llama_param_specs,
    resolve_spec,
)
from fms_fsdp_tpu.train.step import (
    init_train_state,
    make_optimizer,
    make_train_step,
)

TINY = LlamaConfig(
    src_vocab_size=256,
    emb_dim=64,
    nheads=4,
    kvheads=2,
    nlayers=2,
    multiple_of=16,
    max_expected_seq_len=64,
)


def _cfg(**kw):
    base = dict(
        model_variant="tiny",
        seq_length=16,
        batch_size=2,
        num_steps=100,
        learning_rate=1e-2,
        report_interval=10,
        vocab_size=256,
        attention_kernel="xla",
    )
    base.update(kw)
    return TrainConfig(**base)


def _shape(**kw):
    base = {"replica": 1, "fsdp": 1, "expert": 1, "context": 1, "tensor": 1}
    base.update(kw)
    return base


def test_mesh_shapes():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    m = build_mesh(MeshConfig(sharding_strategy="fsdp"))
    assert dict(m.shape) == _shape(fsdp=8)
    m = build_mesh(MeshConfig(sharding_strategy="ddp"))
    assert dict(m.shape) == _shape(replica=8)
    m = build_mesh(MeshConfig(sharding_strategy="hsdp", sharding_group_size=4))
    assert dict(m.shape) == _shape(replica=2, fsdp=4)
    m = build_mesh(MeshConfig(sharding_strategy="fsdp", tensor_parallel_size=2))
    assert dict(m.shape) == _shape(fsdp=4, tensor=2)
    m = build_mesh(
        MeshConfig(sharding_strategy="fsdp", context_parallel_size=2)
    )
    assert dict(m.shape) == _shape(fsdp=4, context=2)
    m = build_mesh(
        MeshConfig(sharding_strategy="fsdp", expert_parallel_size=4)
    )
    assert dict(m.shape) == _shape(fsdp=2, expert=4)
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(sharding_strategy="hsdp", sharding_group_size=3))


def test_resolve_spec_divisibility():
    mesh = build_mesh(MeshConfig(sharding_strategy="fsdp"))
    # 64 divisible by 8 -> kept; 30 not -> dropped
    assert resolve_spec(P("fsdp", None), (64, 3), mesh) == P("fsdp", None)
    assert resolve_spec(P("fsdp", None), (30, 3), mesh) == P(None, None)


def test_llama70b_shardings_resolve():
    """The 70B target config (GQA 64/8 heads, emb 8192) produces valid
    NamedShardings for the full train state on an 8-device FSDP mesh —
    shape-level only (eval_shape; nothing materialized)."""
    from fms_fsdp_tpu.parallel.sharding import tree_shardings
    from fms_fsdp_tpu.train.step import make_optimizer
    from fms_fsdp_tpu.utils.config_utils import get_model_config

    cfg = TrainConfig(sharding_strategy="fsdp", seq_length=4096)
    model_cfg = get_model_config("llama2_70b")
    assert model_cfg.nheads == 64 and model_cfg.n_kv_heads == 8
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)

    from fms_fsdp_tpu.models import get_model_api

    init_params, _, specs_fn, _ = get_model_api(model_cfg)

    def init_fn(rng):
        params = init_params(rng, model_cfg, dtype=jnp.float32)
        return {
            "params": params,
            "opt_state": opt.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    n_params = sum(
        np.prod(s.shape) for s in jax.tree.leaves(shapes["params"])
    )
    assert n_params > 65e9  # truly 70B-scale
    specs = infer_state_specs(shapes, specs_fn())
    shardings = tree_shardings(
        mesh, specs, jax.tree.map(lambda s: s.shape, shapes)
    )
    # every leaf resolves; the big 2D weights actually shard over fsdp
    for leaf in jax.tree.leaves(shardings):
        assert leaf is not None
    assert "fsdp" in str(shardings["params"]["layers"]["wq"].spec)


def test_state_spec_inference():
    cfg = _cfg(sharding_strategy="fsdp")
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    state, shardings = init_train_state(
        jax.random.PRNGKey(0), TINY, cfg, mesh, opt
    )
    # params sharded over fsdp on the model dim
    wq_spec = state["params"]["layers"]["wq"].sharding.spec
    assert wq_spec[1] == "fsdp"
    # adam mu mirrors the param sharding
    mu = state["opt_state"].inner_state[0].mu["layers"]["wq"]
    assert mu.sharding.spec == state["params"]["layers"]["wq"].sharding.spec
    # scalar step replicated
    assert state["step"].sharding.spec == P()


@pytest.mark.parametrize(
    "strategy,extra",
    [
        ("ddp", {}),
        ("fsdp", {}),
        ("hsdp", {"sharding_group_size": 4}),
        ("fsdp", {"tensor_parallel_size": 2}),
    ],
)
def test_train_step_learns(strategy, extra):
    cfg = _cfg(sharding_strategy=strategy, **{k: v for k, v in extra.items()})
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    opt = make_optimizer(cfg)
    state, _ = init_train_state(jax.random.PRNGKey(0), TINY, cfg, mesh, opt)
    step_fn = make_train_step(TINY, cfg, mesh, opt)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(8, 17))
    inputs = jnp.asarray(tokens[:, :-1], jnp.int32)
    labels = jnp.asarray(tokens[:, 1:], jnp.int32)
    labels = labels.at[:, 0].set(-100)  # causal_lm prompt masking analog

    losses = []
    for _ in range(20):
        state, metrics = step_fn(state, (inputs, labels))
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    # memorizing one batch must drive loss down hard
    assert losses[-1] < losses[0] * 0.5, losses
    assert float(metrics["gnorm"]) > 0
    assert int(state["step"]) == 20


def test_strategies_agree():
    """ddp and fsdp are the same math — first-step loss must match."""
    results = {}
    for strategy in ["ddp", "fsdp"]:
        cfg = _cfg(sharding_strategy=strategy)
        mesh = build_mesh(MeshConfig.from_train_config(cfg))
        opt = make_optimizer(cfg)
        state, _ = init_train_state(jax.random.PRNGKey(0), TINY, cfg, mesh, opt)
        step_fn = make_train_step(TINY, cfg, mesh, opt)
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, 256, size=(8, 17))
        inputs = jnp.asarray(tokens[:, :-1], jnp.int32)
        labels = jnp.asarray(tokens[:, 1:], jnp.int32)
        for _ in range(3):
            state, metrics = step_fn(state, (inputs, labels))
        results[strategy] = float(metrics["loss"])
    assert results["ddp"] == pytest.approx(results["fsdp"], rel=2e-2)


def test_base_api_specs_shard_every_arch():
    """Every speculator base arch must ship a spec rulebook so a large
    frozen base is never silently replicated
    (ref:speculator/train_speculator.py:133-160 shards all bases). Big
    weight matrices land sharded, and the sharded forward matches the
    host-side forward."""
    from fms_fsdp_tpu.models import get_base_api
    from fms_fsdp_tpu.models.configs import MixtralConfig
    from fms_fsdp_tpu.models.gpt_bigcode import GPTBigCodeConfig
    from fms_fsdp_tpu.parallel.sharding import shard_params

    cfg = _cfg(sharding_strategy="fsdp")
    mesh = build_mesh(MeshConfig.from_train_config(cfg))

    arch_cfgs = {
        "llama": TINY,
        "gpt_bigcode": GPTBigCodeConfig(
            src_vocab_size=256,
            emb_dim=64,
            nheads=4,
            nlayers=2,
            max_expected_seq_len=64,
        ),
        "mixtral": MixtralConfig(
            src_vocab_size=256,
            emb_dim=64,
            nheads=4,
            kvheads=2,
            nlayers=2,
            hidden_dim=96,
            num_experts=4,
            top_k=2,
            max_expected_seq_len=64,
        ),
    }
    # per arch: one big matrix leaf that MUST be sharded on an fsdp mesh
    must_shard = {
        "llama": lambda p: p["layers"]["w1"],
        "gpt_bigcode": lambda p: p["layers"]["c_fc"],
        "mixtral": lambda p: p["layers"]["w1"],
    }
    for arch, mc in arch_cfgs.items():
        api = get_base_api(arch)
        assert api.param_specs is not None, arch
        params = api.init(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
        logits_host, _ = api.forward_embeds(
            params,
            jnp.zeros((1, 8), jnp.int32),
            mc,
            compute_dtype=jnp.float32,
        )
        sharded = shard_params(params, api.param_specs(), mesh)
        leaf = must_shard[arch](sharded)
        assert not leaf.sharding.is_fully_replicated, (
            arch,
            leaf.shape,
            leaf.sharding,
        )
        logits_dev, _ = api.forward_embeds(
            sharded,
            jnp.zeros((1, 8), jnp.int32),
            mc,
            compute_dtype=jnp.float32,
        )
        np.testing.assert_allclose(
            np.asarray(logits_dev), np.asarray(logits_host), atol=2e-4
        )
