"""Selective-AC mask parity with the reference
(ref:tests/test_selective_ac.py:12-64): for each fraction p, the per-block
remat pattern over a 15-layer model must match exactly."""

import pytest

from fms_fsdp_tpu.parallel.ac import parse_ac_fraction, selective_ac_mask

CASES = [
    (0, [False] * 15),
    (1 / 100, [False] * 15),
    (1 / 5, [False, False, True, False, False] * 3),
    (1 / 3, [False, True, False] * 5),
    (1 / 2, [True, False] * 7 + [True]),
    (3 / 5, [True, False, True, False, True] * 3),
    (2 / 3, [True, False, True] * 5),
    (1, [True] * 15),
    (5 / 3, [True] * 15),
    (-1, [False] * 15),
]


@pytest.mark.parametrize("p,expected", CASES)
def test_selective_ac_mask(p, expected):
    assert selective_ac_mask(15, p) == expected


def test_fraction_strings():
    # CLI delivers fractions as strings (ref:ac_handler.py:45-47)
    assert selective_ac_mask(15, "1/3") == [False, True, False] * 5
    assert parse_ac_fraction("2/3") == pytest.approx(2 / 3)
    assert parse_ac_fraction(0.5) == 0.5
