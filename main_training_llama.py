"""Llama pretraining entry point (ref:main_training_llama.py:25-175).

Same orchestration sequence as the reference — config -> seed -> dist
setup -> mesh/policies -> model -> dataloader -> sharded state -> ckpt
load -> LR schedule -> profiler -> train — with the FSDP wrap, AC
application, torch.compile, and optimizer construction all folded into
the jitted train step + sharded init (train/step.py).

Run:  python main_training_llama.py --model_variant=llama2_7b \\
          --use_dummy_dataset=True --num_steps=100 ...
"""

import os
import sys

import jax

from fms_fsdp_tpu.config import TrainConfig
from fms_fsdp_tpu.data import get_data_loader, get_dummy_loader
from fms_fsdp_tpu.data.device_feed import DeviceFeed
from fms_fsdp_tpu.data.loader import elastic_batch_size, rebatch
from fms_fsdp_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    data_parallel_extent,
)
from fms_fsdp_tpu.train.step import (
    init_train_state,
    make_optimizer,
    make_train_step,
)
from fms_fsdp_tpu.ckpt import build_checkpoint_manager
from fms_fsdp_tpu.utils.cli import parse_cli_args
from fms_fsdp_tpu.utils.config_utils import get_model_config, update_config
from fms_fsdp_tpu.utils.train_utils import (
    get_profiler,
    setup,
    setup_environ_flags,
    train,
)


def main(**kwargs):
    cfg = TrainConfig()
    update_config(cfg, **kwargs)

    if cfg.faults:
        # fault-injection spec from config (tests); the FMS_FAULTS env
        # var is read lazily by the registry when this is empty
        from fms_fsdp_tpu.resilience.faults import configure_faults

        configure_faults(cfg.faults)

    setup()
    setup_environ_flags()

    rank = jax.process_index()
    world_size = jax.process_count()
    if rank == 0:
        print(f"--> running with these configs {cfg}")

    # mesh (replaces FSDP wrapping/sharding policies)
    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    data_extent = data_parallel_extent(mesh)
    if rank == 0:
        print(f"Sharding strategy = {cfg.sharding_strategy}, mesh = {dict(mesh.shape)}")

    # model config; dotted CLI overrides (LlamaConfig.param=value) apply here
    model_cfg = get_model_config(cfg.model_variant)
    update_config(model_cfg, **kwargs)
    if rank == 0:
        print(f"\n--> model has {model_cfg.n_params() / 1e6} Million params\n")

    # checkpoint manager BEFORE the dataloader: an elastic resume
    # (restart on a different topology, docs/checkpointing.md "Elastic
    # resume") must read the previous run's topology fingerprint and
    # resolve the per-rank batch size that preserves the global batch
    # before any per-rank row count is baked into the pipeline
    checkpointer = build_checkpoint_manager(cfg, rank)
    resume_topology = checkpointer.resume_topology()

    # dataloader: per-process stream; batches cover this process's slice of
    # the global batch (batch_size is per data-parallel rank, as in the
    # reference)
    if rank == 0:
        print("Constructing datasets...")
    if data_extent < world_size or data_extent % world_size != 0:
        raise ValueError(
            f"data-parallel extent {data_extent} (replica x fsdp x expert) must be a "
            f"positive multiple of process count {world_size}; lower "
            "tensor/context parallel sizes or add devices"
        )
    if resume_topology:
        cfg.batch_size = elastic_batch_size(
            cfg, resume_topology, data_extent, rank
        )
    # (re)stamp the fingerprint with the RESOLVED batch size: this is
    # what every save writes and what load validates rescales against
    from fms_fsdp_tpu.ckpt.elastic import current_fingerprint

    checkpointer.set_fingerprint(
        current_fingerprint(cfg),
        allow_batch_change=cfg.allow_batch_change,
        allow_corpus_change=getattr(cfg, "allow_corpus_change", False),
    )
    local_batch = cfg.batch_size * (data_extent // world_size)
    if not cfg.use_dummy_dataset:
        loader = get_data_loader(
            cfg, rank, world_size, batch_multiplier=data_extent // world_size
        )
        # interval/final/preemption checkpoints persist this live loader's
        # state next to the model (train_utils.train dataloader=)
        ckpt_loader = loader
    else:
        loader = get_dummy_loader(cfg, rank, world_size)
        ckpt_loader = None  # dummy stream is stateless
    if rank == 0:
        print("Datasets constructed!")

    # sharded train state (jit-init directly into shards: the low_cpu_fsdp /
    # meta-device analog, always on)
    optimizer = make_optimizer(cfg)
    state, _ = init_train_state(
        jax.random.PRNGKey(cfg.seed), model_cfg, cfg, mesh, optimizer
    )

    # checkpoint load (continued pretraining or job restart): the async
    # multi-tier manager built above — blocking snapshot at the step
    # boundary, shard/manifest/commit on a background writer, optional
    # fast local tier alongside the durable one (docs/checkpointing.md)
    # the stateful loader rides along so it restores from the SAME
    # resolved checkpoint dir as the model (data/buffering.py
    # CheckpointDataset.load_from_path): after a fallback resume a
    # loader auto-save can sit AHEAD of the model checkpoint, and the
    # auto-detect alone would silently skip the batches between the two
    state, _, start_step, tokens_seen, is_resuming = checkpointer.load(
        state,
        ckpt_loader,
        # a run-root load path points at its checkpoints/ subdir; a file
        # path loads directly (ref:main_training_llama.py:124-127)
        path=os.path.join(cfg.ckpt_load_path, "checkpoints/")
        if not os.path.isfile(cfg.ckpt_load_path)
        else cfg.ckpt_load_path,
        strict=False,
    )
    if not is_resuming:
        start_step = 0

    step_fn = make_train_step(model_cfg, cfg, mesh, optimizer)

    profiler = get_profiler(cfg, rank)

    # observability (obs/): metrics registry + phase timing + JSONL/CSV
    # sinks + heartbeat; built here so the feed can attribute its own
    # pipeline/staging time into the same registry
    from fms_fsdp_tpu.obs import build_observer

    observer = build_observer(cfg, rank, model_cfg=model_cfg)
    # multi-slice collective split (schema v5): the report-cadence probe
    # times one within-slice (ICI) and one cross-slice (DCN) reduce per
    # window so cross-slice overhead is attributable; None (and zero
    # cost) on single-slice meshes. When the step above resolved a DCN
    # overlap schedule (parallel/overlap.py), the probe replays it — one
    # reduce per bucket at real wire bytes — and the observer derives
    # the v10 dcn_overlap_frac from the same schedule.
    from fms_fsdp_tpu.obs.collectives import make_collective_split_probe
    from fms_fsdp_tpu.parallel.overlap import plan_summary

    overlap_schedule = plan_summary()
    observer.attach_collective_probe(
        make_collective_split_probe(
            mesh, observer.timer, schedule=overlap_schedule
        )
    )
    observer.attach_overlap_schedule(overlap_schedule)

    # batch loop: stack per-rank batches to the local device batch
    feed = DeviceFeed(
        rebatch(loader, local_batch, cfg.batch_size),
        mesh,
        prefetch=max(0, int(getattr(cfg, "feed_prefetch", 2))),
        registry=observer.registry,
    )

    if rank == 0:
        print(f"Training for {cfg.num_steps} steps")
    train(
        cfg,
        state,
        step_fn,
        rank,
        iter(feed),
        profiler,
        checkpointer,
        start_step,
        tokens_seen,
        dataloader=ckpt_loader,
        model_cfg=model_cfg,
        observer=observer,
    )


if __name__ == "__main__":
    # classified failures (anomaly abort, classified slice loss, loader
    # death) exit with their registry code (resilience/exits.py) so the
    # self-healing supervisor maps exit -> restart policy
    from fms_fsdp_tpu.resilience.exits import classified_exit

    with classified_exit():
        main(**parse_cli_args(sys.argv[1:]))
