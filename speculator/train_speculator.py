"""Speculator training entry point (ref:speculator/train_speculator.py:107-326).

Sequence: config -> mesh -> frozen base model (loaded from
cfg.model_path) -> sanity generation test -> MLPSpeculator (replicated —
the NO_SHARD analog) -> dataloader (raw packed sequences, no causal
shift) -> two-stage training loop.

Run:  python speculator/train_speculator.py --model_variant=llama2_7b \\
          --model_path=/path/to/ckpt --use_dummy_dataset=True ...
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from fms_fsdp_tpu.config import TrainConfig
from fms_fsdp_tpu.data import get_data_loader, get_dummy_loader
from fms_fsdp_tpu.data.device_feed import DeviceFeed
from fms_fsdp_tpu.data.loader import rebatch
from fms_fsdp_tpu.models import get_base_api
from fms_fsdp_tpu.models.hf_import import is_hf_checkpoint, load_hf_base
from fms_fsdp_tpu.models.speculator import (
    SpeculatorConfig,
    init_speculator_params,
)
from fms_fsdp_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    data_parallel_extent,
)
from fms_fsdp_tpu.parallel.sharding import shard_params
from fms_fsdp_tpu.train.speculator import (
    make_speculator_optimizer,
    train_speculator,
)
from fms_fsdp_tpu.utils.checkpointing import Checkpointer
from fms_fsdp_tpu.utils.cli import parse_cli_args
from fms_fsdp_tpu.utils.config_utils import get_model_config, update_config
from fms_fsdp_tpu.utils.train_utils import (
    get_profiler,
    setup,
    setup_environ_flags,
)


def test_model(rank, base_params, model_cfg, cfg, base_api):
    """Sanity generation check on the loaded base model
    (ref:speculator/train_speculator.py:34-60 analog)."""
    prompt = jnp.arange(16, dtype=jnp.int32)[None, :] % model_cfg.src_vocab_size
    out = base_api.generate(
        base_params,
        prompt,
        model_cfg,
        key=jax.random.PRNGKey(0),
        max_seq_len=64,
        max_new_tokens=8,
        do_sample=False,
        include_embeds=False,
    )
    if rank == 0:
        print(f"{time.time()} sanity generation:", np.asarray(out[0, -8:]))


def main(**kwargs):
    cfg = TrainConfig()
    update_config(cfg, **kwargs)
    # room for the ground-truth targets of every head
    cfg.seq_length = cfg.seq_length + cfg.n_speculator_heads + 1

    setup()
    setup_environ_flags()
    rank = jax.process_index()
    world_size = jax.process_count()
    if rank == 0:
        print(f"{time.time()} running with these configs {cfg}")

    # base-model mesh: "tp" shards the base over the tensor axis
    # (ref:train_speculator.py:133-142); other strategies shard FSDP-style
    mesh_cfg = MeshConfig(
        sharding_strategy=cfg.sharding_strategy,
        sharding_group_size=cfg.sharding_group_size,
        tensor_parallel_size=cfg.tp_size if cfg.sharding_strategy == "tp" else 1,
    )
    mesh = build_mesh(mesh_cfg)

    # frozen base model. Three sources, mirroring the reference's
    # fms.models.get_model(arch, variant, model_path, source="hf"|...)
    # (ref:speculator/train_speculator.py:115-131):
    #   1. an HF-format checkpoint dir at model_path (any supported arch),
    #   2. a native checkpoint at model_path (llama),
    #   3. random init (smoke-test mode).
    base_api = get_base_api(cfg.model_arch)
    if cfg.model_path and is_hf_checkpoint(cfg.model_path):
        arch, model_cfg, base_params = load_hf_base(cfg.model_path)
        if arch != base_api.arch:
            if rank == 0:
                print(f"model_arch={cfg.model_arch} overridden by HF "
                      f"checkpoint arch {arch}")
            base_api = get_base_api(arch)
        base_params = shard_params(base_params, base_api.param_specs(), mesh)
    else:
        if base_api.arch == "llama":
            model_cfg = get_model_config(cfg.model_variant)
        else:
            from fms_fsdp_tpu.models.gpt_bigcode import GPTBigCodeConfig
            from fms_fsdp_tpu.models.mixtral import MixtralConfig

            model_cfg = (
                GPTBigCodeConfig()
                if base_api.arch == "gpt_bigcode"
                else MixtralConfig()
            )
        update_config(model_cfg, **kwargs)
        base_params = base_api.init(
            jax.random.PRNGKey(cfg.seed), model_cfg, dtype=jnp.bfloat16
        )
        base_params = shard_params(base_params, base_api.param_specs(), mesh)
        if cfg.model_path and os.path.exists(cfg.model_path):
            loader_ck = Checkpointer(
                os.path.join(cfg.ckpt_save_path, "_base_load"), 1, "ddp", rank
            )
            state = {"params": base_params}
            state, _, _, _, _ = loader_ck.load(state, None, path=cfg.model_path)
            base_params = state["params"]
        elif rank == 0:
            print(
                f"No base checkpoint at {cfg.model_path}; using random init "
                "(smoke-test mode)"
            )

    test_model(rank, base_params, model_cfg, cfg, base_api)

    # speculator (replicated: NO_SHARD analog, ref:train_speculator.py:201)
    scfg = SpeculatorConfig.from_train_config(
        cfg, emb_dim=model_cfg.emb_dim, vocab_size=model_cfg.src_vocab_size
    )
    spec_params = init_speculator_params(jax.random.PRNGKey(cfg.seed + 1), scfg)
    if rank == 0:
        print(
            f"\n{time.time()} speculator has {scfg.n_params() / 1e6} "
            "Million params\n"
        )

    # data: raw packed sequences (no causal shift), assembled into global
    # mesh-sharded batches covering the data-parallel extent
    data_extent = data_parallel_extent(mesh)
    local_batch = cfg.batch_size * max(1, data_extent // world_size)
    if not cfg.use_dummy_dataset:
        train_loader = get_data_loader(
            cfg, rank, world_size, postprocess=[],
            batch_multiplier=max(1, data_extent // world_size),
        )
    else:
        train_loader = get_dummy_loader(cfg, rank, world_size)
    # observability: same metrics.jsonl/heartbeat contract as the
    # pretraining entries (docs/observability.md); MFU is null — the
    # run's FLOPs are dominated by the frozen base, not the speculator
    from fms_fsdp_tpu.obs import build_observer
    from fms_fsdp_tpu.obs.collectives import make_collective_split_probe

    observer = build_observer(cfg, rank)
    # multi-slice collective split (schema v5): None / zero cost on the
    # usual single-slice speculator mesh, same wiring as the pretraining
    # entries
    observer.attach_collective_probe(
        make_collective_split_probe(mesh, observer.timer)
    )
    feed = DeviceFeed(
        rebatch(train_loader, local_batch, cfg.batch_size),
        mesh,
        prefetch=max(0, int(getattr(cfg, "feed_prefetch", 2))),
        registry=observer.registry,
    )

    optimizer = make_speculator_optimizer(cfg)
    spec_state = {
        "params": spec_params,
        "opt_state": optimizer.init(spec_params),
        "step": jnp.zeros((), jnp.int32),
    }

    # async multi-tier manager (ckpt/): same blocking-snapshot /
    # background-commit contract as the pretraining entries; the
    # speculator state is replicated, so parallel_mode is ddp
    from fms_fsdp_tpu.ckpt import build_checkpoint_manager

    checkpointer = build_checkpoint_manager(cfg, rank, parallel_mode="ddp")
    ckpt_loader = train_loader if hasattr(train_loader, "save_to_path") else None
    spec_state, _, start_step, tokens_seen, _ = checkpointer.load(
        spec_state,
        ckpt_loader,
        path=os.path.join(cfg.ckpt_load_path, "checkpoints/"),
    )

    profiler = get_profiler(cfg, rank)

    if rank == 0:
        print(f"{time.time()} Training for {cfg.num_steps} steps")
    train_speculator(
        cfg,
        base_params,
        model_cfg,
        spec_state,
        scfg,
        rank,
        iter(feed),
        optimizer,
        checkpointer,
        start_step,
        tokens_seen,
        profiler,
        ckpt_loader=ckpt_loader,
        base_api=base_api,
        mesh=mesh,
        observer=observer,
    )


if __name__ == "__main__":
    # classified-exit mapping for the self-healing supervisor, same as
    # the pretraining entries (resilience/exits.py)
    from fms_fsdp_tpu.resilience.exits import classified_exit

    with classified_exit():
        main(**parse_cli_args(sys.argv[1:]))
